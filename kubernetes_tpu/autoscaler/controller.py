"""The cluster-autoscaler control loop.

Behavioral equivalent of the reference cluster-autoscaler's
``core/static_autoscaler.go`` RunOnce: each tick (1) collects the
unschedulable trigger set (scheduling-queue leftovers when a queue is
attached, plus pods carrying a FailedScheduling/Unschedulable
condition), (2) if anything is pending and the scale-up cooldown has
passed, runs ONE batched what-if solve per candidate node group
(``simulator.plan_scale_up`` — virtual template-node columns appended
to the encoded planes, NOT a per-pod loop), lets the expander
(least-waste | priority) choose a group, and provisions the read-off
node count within the group's max size; (3) when nothing is pending,
scans the cluster for scale-down candidates — group nodes below the
utilization threshold whose pods all fit elsewhere (the same virtual-
solve machinery with the candidate's column REMOVED) — and, after
``scale_down_unneeded_time`` of continuous unneededness, drives the
drain pipeline: cordon → PDB-respecting eviction (consulting the
disruption controller's published ``status.disruptions_allowed``) →
node deletion once empty.

The loop rides the shared controller scaffolding (tick → workqueue →
worker) and is leader-electable via ``run_with_leader_election`` (the
reference deploys one replica with lease-based leader election).
"""

from __future__ import annotations

import copy
import time
from typing import Dict, List, Optional

from kubernetes_tpu.api.types import FAILED, SUCCEEDED, Node, Pod
from kubernetes_tpu.autoscaler.nodegroups import (
    NodeGroupRegistry,
    SAFE_TO_EVICT_ANNOTATION,
    SimulatedProvisioner,
)
from kubernetes_tpu.controllers.base import Controller, controller_of
from kubernetes_tpu.metrics.autoscaler_metrics import autoscaler_metrics
from kubernetes_tpu.scheduler.types import (
    compute_pod_resource_request,
    get_pod_key,
)


class ClusterAutoscaler(Controller):
    name = "clusterautoscaler"
    workers = 1
    RESYNC_SECONDS = 0.25           # reference --scan-interval (10s), scaled

    # -- knobs (class-level so harnesses override like nodelifecycle's)
    expander = "least-waste"        # or "priority"
    scale_up_cooldown = 2.0         # min seconds between scale-up decisions
    max_virtual_per_group = 64      # K cap per what-if solve
    max_whatif_pods = 2048          # pending-set sample cap per solve
    scale_down_enabled = True
    scale_down_utilization_threshold = 0.5   # max(cpu,mem) requested frac
    scale_down_unneeded_time = 3.0  # reference --scale-down-unneeded-time
    max_concurrent_drains = 1
    pending_age_backstop = 3.0      # store-scan fallback trigger age (s)

    def __init__(self, store, factory,
                 registry: Optional[NodeGroupRegistry] = None,
                 provisioner: Optional[SimulatedProvisioner] = None):
        self.registry = registry if registry is not None \
            else NodeGroupRegistry()
        self.provisioner = provisioner if provisioner is not None \
            else SimulatedProvisioner(store, self.registry)
        # optional SchedulingQueue: when the scheduler is colocated, its
        # unschedulableQ IS the trigger surface (exact, no heuristics)
        self.queue_introspect = None
        self.metrics = autoscaler_metrics()
        self.whatif_solves = 0      # batched solves issued (test hook)
        self.scale_up_events = 0
        self.scale_down_events = 0
        self.elector = None
        self._last_scale_up = 0.0
        self._pending_first_seen: Optional[float] = None
        self._unneeded_since: Dict[str, float] = {}
        self._draining: Dict[str, str] = {}   # node name -> group name
        # persistent eviction ledger per PDB: [resource_version, used].
        # status.disruptions_allowed lags our deletions by a disruption-
        # controller resync; without remembering what this loop already
        # spent against the OBSERVED status generation, consecutive
        # passes would re-read the stale budget and over-evict. A status
        # recompute bumps the PDB's resourceVersion, resetting the entry.
        self._pdb_spent: Dict[str, list] = {}
        super().__init__(store, factory)

    # -- controller scaffolding ----------------------------------------
    def register(self) -> None:
        # tick-driven (the reference CA polls on --scan-interval); no
        # event handlers — the what-if reads store truth each pass
        pass

    def resync(self) -> None:
        self.enqueue_key("reconcile")

    def sync(self, key: str) -> None:
        self.reconcile_once()

    def run(self) -> None:
        self.provisioner.start()
        super().run()

    def stop(self) -> None:
        super().stop()
        self.provisioner.stop()
        if self.elector is not None:
            self.elector.stop()

    def run_with_leader_election(
        self, identity: str = "cluster-autoscaler-0",
        lease_name: str = "cluster-autoscaler",
        lease_duration: float = 15.0, renew_deadline: float = 10.0,
        retry_period: float = 2.0, clock=None,
    ):
        """Only the lease holder runs the loop (one elastic brain per
        cluster — two concurrent autoscalers would double-provision).
        Losing the lease stops this instance for good, mirroring the
        scheduler's fatal-on-deposed posture."""
        from kubernetes_tpu.client.leaderelection import (
            LeaderElectionConfig,
            LeaderElector,
        )

        cfg = LeaderElectionConfig(
            lock_name=lease_name, identity=identity,
            lease_duration=lease_duration, renew_deadline=renew_deadline,
            retry_period=retry_period,
            on_started_leading=self.run,
            on_stopped_leading=self._on_lost_lease,
        )
        self.elector = LeaderElector(self.store, cfg, clock=clock)
        self.elector.run_in_thread()
        return self.elector

    def _on_lost_lease(self) -> None:
        if not self._stopped:
            self.stop()

    # -- the reconcile pass --------------------------------------------
    def reconcile_once(self) -> None:
        if not len(self.registry):
            # default-registered in every ControllerManager: with no
            # groups there is nothing to scale, so don't pay the
            # per-tick store scan (or publish a bogus pending gauge)
            return
        now = time.monotonic()
        # ONE pod-list snapshot per tick: the elastic bench runs this
        # loop at 10 Hz beside a 30k-pod scheduler, and each extra
        # store scan is GIL time stolen from the bind path
        pods = self.store.list_pods()
        self._continue_drains(pods)
        pending = self.pending_unschedulable(pods)
        self.metrics.pending_unschedulable.set(float(len(pending)))
        if pending:
            if self._pending_first_seen is None:
                self._pending_first_seen = now
            if now - self._last_scale_up >= self.scale_up_cooldown:
                self._scale_up(pods, pending, now)
        else:
            if self._pending_first_seen is not None:
                self.metrics.time_to_capacity_seconds.observe(
                    now - self._pending_first_seen)
                self._pending_first_seen = None
            if self.scale_down_enabled:
                self._scale_down(pods, now)

    # -- trigger surface -----------------------------------------------
    def pending_unschedulable(self,
                              pods: Optional[List[Pod]] = None) -> List[Pod]:
        """Queue leftovers + FailedScheduling outcomes: the pods whose
        existence justifies buying nodes. Bound, terminal and
        terminating pods never count; without queue introspection an
        age backstop catches pods the scheduler never got to."""
        out: Dict[str, Pod] = {}
        q = self.queue_introspect
        if q is not None:
            # same liveness filters as the store scan: a pod deleted or
            # bound in the store lingers in the queue until the informer
            # event lands, and must not trigger (or keep alive) a solve
            for pod in q.unschedulable_pods():
                if pod.spec.node_name or \
                        pod.metadata.deletion_timestamp is not None or \
                        pod.status.phase in (SUCCEEDED, FAILED):
                    continue
                out[get_pod_key(pod)] = pod
        now_wall = time.time()
        for pod in (pods if pods is not None else self.store.list_pods()):
            if pod.spec.node_name or \
                    pod.metadata.deletion_timestamp is not None:
                continue
            if pod.status.phase in (SUCCEEDED, FAILED):
                continue
            key = get_pod_key(pod)
            if key in out:
                continue
            if any(c.type == "PodScheduled" and c.status == "False"
                   and c.reason == "Unschedulable"
                   for c in pod.status.conditions):
                out[key] = pod
            elif q is None and pod.metadata.creation_timestamp and \
                    now_wall - pod.metadata.creation_timestamp \
                    >= self.pending_age_backstop:
                out[key] = pod
        return list(out.values())

    # -- scale-up -------------------------------------------------------
    @staticmethod
    def _live_bound_pods(pods: List[Pod]) -> List[Pod]:
        return [
            p for p in pods
            if p.spec.node_name and p.status.phase not in (SUCCEEDED, FAILED)
            and p.metadata.deletion_timestamp is None
        ]

    def _scale_up(self, pods: List[Pod], pending: List[Pod],
                  now: float) -> None:
        # lazy: the simulator pulls in the jax solver, which jax-free
        # processes constructing (but never scaling) this controller
        # must not pay for
        from kubernetes_tpu.autoscaler.simulator import plan_scale_up

        groups = []
        for group in self.registry:
            headroom = group.max_size - self.provisioner.group_size(
                group.name)
            if headroom > 0:
                groups.append((group, headroom))
        if not groups:
            return
        # upcoming BEFORE the node list: a node registering between the
        # two reads then shows up twice (harmless — upcoming columns
        # only absorb pods) instead of in neither (a re-buy)
        upcoming = self.provisioner.booting_templates()
        plan = plan_scale_up(
            self.store.list_nodes(), self._live_bound_pods(pods), pending,
            groups, expander=self.expander,
            upcoming=upcoming,
            max_virtual=self.max_virtual_per_group,
            max_pods=self.max_whatif_pods,
        )
        self.whatif_solves += plan.solves
        # the cooldown gates plan ATTEMPTS, not just purchases: a
        # pending pod no group can help would otherwise re-run a full
        # encode + solve per group every tick, forever
        self._last_scale_up = now
        best = plan.chosen
        if best is None or best.nodes_needed <= 0:
            return
        group = self.registry.get(best.group)
        self.provisioner.provision(group, best.nodes_needed)
        self.scale_up_events += 1
        self.metrics.scaleups_total.inc(
            best.group, self.expander, amount=best.nodes_needed)

    # -- scale-down -----------------------------------------------------
    @staticmethod
    def _drainable(pod: Pod) -> bool:
        """Upstream refuses to delete nodes holding pods nothing will
        recreate, unless the pod opts in via the safe-to-evict
        annotation."""
        if controller_of(pod) is not None:
            return True
        return pod.metadata.annotations.get(
            SAFE_TO_EVICT_ANNOTATION) == "true"

    @staticmethod
    def _utilization(node: Node, pods: List[Pod]) -> float:
        alloc = node.status.allocatable
        cpu_alloc = int(alloc["cpu"].milli_value()) if "cpu" in alloc else 0
        mem_alloc = int(alloc["memory"].value()) if "memory" in alloc else 0
        cpu_used = mem_used = 0
        for p in pods:
            r = compute_pod_resource_request(p)
            cpu_used += r.milli_cpu
            mem_used += r.memory
        fracs = []
        if cpu_alloc:
            fracs.append(cpu_used / cpu_alloc)
        if mem_alloc:
            fracs.append(mem_used / mem_alloc)
        return max(fracs) if fracs else 0.0

    def _scale_down(self, pods: List[Pod], now: float) -> None:
        from kubernetes_tpu.autoscaler.simulator import pods_fit_elsewhere

        nodes = self.store.list_nodes()
        bound = self._live_bound_pods(pods)
        pods_by_node: Dict[str, List[Pod]] = {}
        for p in bound:
            pods_by_node.setdefault(p.spec.node_name, []).append(p)
        sizes = {g.name: self.provisioner.group_size(g.name)
                 for g in self.registry}
        draining_per_group: Dict[str, int] = {}
        for g in self._draining.values():
            draining_per_group[g] = draining_per_group.get(g, 0) + 1
        live_names = set()
        for node in sorted(nodes, key=lambda n: n.name):
            name = node.name
            live_names.add(name)
            if name in self._draining:
                continue
            gname = NodeGroupRegistry.group_of(node)
            group = self.registry.get(gname) if gname else None
            if group is None:
                self._unneeded_since.pop(name, None)
                continue
            budget = sizes[gname] - group.min_size \
                - draining_per_group.get(gname, 0)
            its_pods = pods_by_node.get(name, [])
            unneeded = (
                budget > 0
                and not node.spec.unschedulable
                and self._utilization(node, its_pods)
                < self.scale_down_utilization_threshold
                and all(self._drainable(p) for p in its_pods)
            )
            if not unneeded:
                self._unneeded_since.pop(name, None)
                continue
            since = self._unneeded_since.setdefault(name, now)
            if now - since < self.scale_down_unneeded_time:
                continue
            # _draining already includes this pass's starts
            if len(self._draining) >= self.max_concurrent_drains:
                continue
            if its_pods:
                # the expensive gate LAST, and only once the unneeded
                # timer matured (the cheap gates keep the timer honest
                # each tick; re-solving fit-elsewhere every tick of the
                # window would buy nothing — state can still change up
                # to the cordon, which is the moment this verdict gates)
                self.whatif_solves += 1
                if not pods_fit_elsewhere(nodes, bound, name, its_pods):
                    self._unneeded_since.pop(name, None)
                    continue
            self._cordon(name)
            self._draining[name] = gname
            draining_per_group[gname] = draining_per_group.get(gname, 0) + 1
            self._unneeded_since.pop(name, None)
        for name in list(self._unneeded_since):
            if name not in live_names:
                del self._unneeded_since[name]

    def _cordon(self, name: str, on: bool = True) -> None:
        node = self.store.get_node(name)
        if node is None:
            return
        node = copy.copy(node)
        node.metadata = copy.copy(node.metadata)
        node.spec = copy.copy(node.spec)
        node.spec.unschedulable = on
        self.store.update_node(node)

    def _uncordon(self, name: str) -> None:
        self._cordon(name, on=False)

    def _continue_drains(self, pods: List[Pod]) -> None:
        """Advance every in-flight drain: evict what the PDBs allow;
        delete the node once empty. A blocked eviction just waits for
        the next pass (the disruption controller will raise
        disruptions_allowed as replacements land elsewhere)."""
        if not self._draining:
            return
        by_node: Dict[str, List[Pod]] = {}
        for p in pods:
            if p.spec.node_name and p.metadata.deletion_timestamp is None \
                    and p.status.phase not in (SUCCEEDED, FAILED):
                by_node.setdefault(p.spec.node_name, []).append(p)
        for name in sorted(self._draining):
            gname = self._draining[name]
            if self.store.get_node(name) is None:
                # vanished underneath us (churn): nothing left to delete
                self._draining.pop(name)
                continue
            its_pods = by_node.get(name, [])
            if not its_pods:
                self.provisioner.deprovision(name)
                self._draining.pop(name)
                self.scale_down_events += 1
                self.metrics.scaledowns_total.inc(gname)
                continue
            if not all(self._drainable(p) for p in its_pods):
                # a non-drainable pod bound in the scan→cordon window
                # (the commit guard only sees the cordon after informer
                # delivery): the node is needed after all — abandon the
                # drain rather than stall cordoned forever or delete a
                # pod nothing will recreate
                self._uncordon(name)
                self._draining.pop(name)
                continue
            for pod in its_pods:
                if not self._pdb_allows(pod):
                    continue
                self.store.delete_pod(pod.namespace, pod.metadata.name)
                self.metrics.evicted_for_scaledown_total.inc()

    def _pdb_allows(self, pod: Pod) -> bool:
        """Eviction-API semantics against the disruption controller's
        published state: every PDB matching the pod must have budget
        left; a granted eviction consumes one unit from each.
        ``status.disruptions_allowed`` lags our deletions until the
        disruption controller resyncs, so spends are remembered in
        ``_pdb_spent`` keyed on the PDB's resourceVersion — a status
        recompute bumps the version and resets the ledger, and until
        then the stale budget can't be spent twice."""
        matching = [
            pdb for pdb in self.store.list_pdbs()
            if pdb.namespace == pod.namespace
            and pdb.selector.matches(pod.metadata.labels)
        ]

        def spent(pdb) -> int:
            ent = self._pdb_spent.get(f"{pdb.namespace}/{pdb.name}")
            if ent is not None and ent[0] == pdb.metadata.resource_version:
                return ent[1]
            return 0

        for pdb in matching:
            if pdb.status.disruptions_allowed - spent(pdb) <= 0:
                return False
        for pdb in matching:
            key = f"{pdb.namespace}/{pdb.name}"
            rv = pdb.metadata.resource_version
            ent = self._pdb_spent.get(key)
            if ent is not None and ent[0] == rv:
                ent[1] += 1
            else:
                self._pdb_spent[key] = [rv, 1]
        return True
