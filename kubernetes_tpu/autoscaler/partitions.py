"""Load-aware partition rebalancing + control-plane autoscaling.

The cluster autoscaler (PR 4) buys and retires NODES when pods don't
fit; this module applies the same discipline to the CONTROL PLANE
itself: apiserver partitions become a scaled resource. A
``PartitionRebalancer`` — a controller on the shared scaffolding
(resync tick → workqueue → sync worker) — watches the per-partition
write ledgers (mirrored into the PR 8 metrics federation), detects the
hotspot shapes the static PR 9 layout cannot answer, and drives the
live-resharding machinery:

- one namespace dominating the write load → **split** (spread the
  namespace's keyspace across every slot, ``spread_namespace``);
- a hot partition with movable slots → **move** (reassign its
  hottest slots to the coldest partition, ``migrate_slots``);
- the whole fleet hot and nothing left to move → **buy** a partition
  through the ``PartitionGroup`` (min/max/cooldown — the NodeGroup
  contract, pointed at apiserver processes instead of kubelets) and
  drain an even share of slots onto it;
- a near-idle fleet → **retire** the least-loaded partition back to
  the group's floor;
- a dead partition (stats unreachable) → **failover**: restart it
  from its WAL segment and re-point the topology.

Decisions are a PURE function (``plan_rebalance``) over the observed
per-slot/per-namespace write rates — unit-testable without a fleet —
and every action is bounded by the group's cooldown so a noisy signal
cannot thrash migrations.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

_logger = logging.getLogger(__name__)


@dataclass
class PartitionGroup:
    """Scaling bounds for the apiserver fleet — the cloudprovider
    NodeGroup contract applied to control-plane processes."""

    name: str = "control-plane"
    min_partitions: int = 1
    max_partitions: int = 8
    cooldown_s: float = 3.0


@dataclass
class RebalancePolicy:
    """Thresholds for the pure planner."""

    imbalance_threshold: float = 1.6   # max/mean rate before acting
    spread_share: float = 0.45         # one ns above this share → split
    min_rate: float = 20.0             # writes/tick to bother at all
    sustain_ticks: int = 2             # consecutive hot ticks to act
    move_headroom: float = 1.1         # move until hot ≤ headroom×mean
    max_moves: int = 8                 # slots per move action
    buy_rate: float = 400.0            # mean rate/partition → saturated
    buy_floor_share: float = 0.6       # coldest ≥ this share of mean
    retire_rate: float = 2.0           # per-partition rate ≈ idle


def plan_rebalance(slot_rates: Dict[int, float],
                   ns_rates: Dict[str, float],
                   topology,
                   dead: List[int],
                   policy: RebalancePolicy,
                   group: PartitionGroup) -> Optional[Dict[str, Any]]:
    """One rebalancing decision from one tick's observations. Pure:
    (rates, topology, liveness) → action or None.

    Priority: failover beats everything (a silent shard is worse than
    a hot one); then split > move > buy (cheapest fix first: spreading
    a tenant touches one namespace, moving touches whole slots, buying
    costs a process)."""
    if dead:
        return {"op": "failover", "partition": dead[0]}
    live = [p for p in range(topology.partitions)
            if p not in topology.retired and p not in dead]
    if not live:
        return None
    rates = {p: 0.0 for p in live}
    for slot, rate in slot_rates.items():
        owner = topology.owner[slot]
        if owner in rates:
            rates[owner] += rate
    total = sum(rates.values())
    if total < policy.min_rate:
        # idle fleet: fold the floor back in
        if len(live) > group.min_partitions \
                and total < policy.retire_rate * len(live):
            coldest = min(live, key=lambda p: rates[p])
            if topology.slots_of_partition(coldest):
                return {"op": "retire", "partition": coldest}
        return None
    mean = total / len(live)
    hot = max(live, key=lambda p: rates[p])
    coldest = min(live, key=lambda p: rates[p])
    imbalance = rates[hot] / mean if mean > 0 else 0.0
    if imbalance >= policy.imbalance_threshold:
        # 1. SPLIT: one tenant dominating the hot shard
        if ns_rates:
            hot_ns = max(ns_rates, key=ns_rates.get)
            ns_total = sum(ns_rates.values())
            if ns_total > 0 \
                    and ns_rates[hot_ns] / ns_total \
                    >= policy.spread_share \
                    and hot_ns not in topology.spread:
                return {"op": "split", "namespace": hot_ns}
        # 2. MOVE: reassign the hot partition's hottest slots to the
        # coldest
        movable = sorted(
            (s for s in topology.slots_of_partition(hot)
             if slot_rates.get(s, 0.0) > 0),
            key=lambda s: slot_rates.get(s, 0.0), reverse=True)
        assignments: Dict[int, int] = {}
        projected_hot = rates[hot]
        for s in movable:
            if projected_hot <= policy.move_headroom * mean \
                    or len(assignments) >= policy.max_moves:
                break
            rate = slot_rates.get(s, 0.0)
            if rate >= rates[hot] * 0.9 and len(movable) > 1:
                # one slot IS the hotspot: moving it just moves the
                # problem (that is the split's job, handled above)
                continue
            assignments[s] = coldest
            projected_hot -= rate
        if assignments:
            return {"op": "move", "assignments": assignments}
    # 3. BUY: the whole fleet is saturated — balanced (no imbalance to
    # fix) or nothing movable helped — and every shard is genuinely
    # busy: more partitions is the only lever left. This is the
    # control-plane twin of the node autoscaler's scale-up.
    if len(live) < group.max_partitions \
            and mean >= policy.buy_rate \
            and rates[coldest] >= policy.buy_floor_share * mean:
        return {"op": "buy"}
    return None


# ---------------------------------------------------------------------------
# drivers: the rebalancer's hands (in-process store / REST coordinator)


class InprocElasticDriver:
    """Drive a ``PartitionedStore`` (reshardable=True) directly."""

    def __init__(self, store,
                 provisioner: Optional[Callable[[], int]] = None):
        self.store = store
        self._provisioner = provisioner

    def observe(self) -> dict:
        stats = self.store.reshard_stats()
        return {
            "epoch": stats["epoch"],
            "topology": self.store.topology,
            "slot_writes": {int(k): v
                            for k, v in stats["slot_writes"].items()},
            "ns_writes": dict(stats["ns_writes"]),
            "dead": [],
        }

    def federate(self) -> None:
        from kubernetes_tpu.metrics.federation import metrics_federation

        fed = metrics_federation()
        for i, reg in enumerate(self.store.partition_registries()):
            fed.forget_instance(f"partition-{i}")
            fed.absorb_registry(reg, instance=f"partition-{i}")

    def apply(self, action: Dict[str, Any]) -> dict:
        op = action["op"]
        if op == "split":
            return self.store.spread_namespace(action["namespace"])
        if op == "move":
            return self.store.migrate_slots(action["assignments"])
        if op == "retire":
            return self.store.retire_partition(action["partition"])
        if op == "failover":
            return self.store.restart_partition(action["partition"])
        if op == "buy":
            if self._provisioner is not None:
                idx = self._provisioner()
            else:
                idx = self.store.add_partition()
            # drain an even share onto the new partition
            topo = self.store.topology
            want = topo.slots // (len(self.store.parts))
            counts: Dict[int, int] = {}
            for o in topo.owner:
                counts[o] = counts.get(o, 0) + 1
            moves: Dict[int, int] = {}
            for p in sorted(counts, key=counts.get, reverse=True):
                for s in topo.slots_of_partition(p):
                    if len(moves) >= want or counts[p] <= want:
                        break
                    moves[s] = idx
                    counts[p] -= 1
            report = self.store.migrate_slots(moves) if moves else {}
            report["new_partition"] = idx
            return report
        raise ValueError(f"unknown rebalance op {op!r}")


class RestElasticDriver:
    """Drive a fleet of partition apiservers through a
    ``ReshardCoordinator``; ``provisioner`` boots a new server process
    and returns its URL (buy), ``restarter(index)`` WAL-restores a dead
    one and returns its URL (failover)."""

    def __init__(self, coordinator,
                 provisioner: Optional[Callable[[], str]] = None,
                 restarter: Optional[Callable[[int], str]] = None,
                 federate: bool = True):
        self.coordinator = coordinator
        self._provisioner = provisioner
        self._restarter = restarter
        # ``federate=False`` for IN-PROCESS partition servers: they
        # share this process's default registry, and folding a
        # registry's own counters back into itself re-counts them
        # every tick (compounding) — the fold contract is for CHILD
        # processes only
        self._federate = bool(federate)

    def observe(self) -> dict:
        stats = self.coordinator.stats()
        topo = self.coordinator.fetch_topology()
        slot_writes: Dict[int, float] = {}
        ns_writes: Dict[str, float] = {}
        dead: List[int] = []
        for s in stats:
            if not s.get("alive"):
                dead.append(int(s.get("partition", 0)))
                continue
            for k, v in (s.get("slot_writes") or {}).items():
                slot_writes[int(k)] = slot_writes.get(int(k), 0) + v
            for k, v in (s.get("ns_writes") or {}).items():
                ns_writes[k] = ns_writes.get(k, 0) + v
        return {"epoch": topo.epoch, "topology": topo,
                "slot_writes": slot_writes, "ns_writes": ns_writes,
                "dead": dead}

    def federate(self) -> None:
        if not self._federate:
            return
        from kubernetes_tpu.metrics.federation import metrics_federation

        fed = metrics_federation()
        client = self.coordinator.client
        token = getattr(client, "token", "")
        for i, url in enumerate(client.partition_urls):
            fed.forget_instance(f"apiserver-p{i}")
            try:
                fed.scrape(url, instance=f"apiserver-p{i}",
                           token=token, fold=True)
            except Exception:  # noqa: BLE001 — best-effort per child
                pass

    def apply(self, action: Dict[str, Any]) -> dict:
        op = action["op"]
        if op == "split":
            return self.coordinator.spread_namespace(action["namespace"])
        if op == "move":
            return self.coordinator.move_slots(action["assignments"])
        if op == "retire":
            return self.coordinator.retire(action["partition"])
        if op == "failover":
            if self._restarter is None:
                raise RuntimeError(
                    "failover requires a restarter(index) hook")
            url = self._restarter(action["partition"])
            return self.coordinator.reroute_after_restart(
                action["partition"], url)
        if op == "buy":
            if self._provisioner is None:
                raise RuntimeError("buy requires a provisioner hook")
            return self.coordinator.split_to(self._provisioner())
        raise ValueError(f"unknown rebalance op {op!r}")


# ---------------------------------------------------------------------------
# the controller


class PartitionRebalancer:
    """The control loop: observe ledgers → plan (pure) → act (driver),
    on the shared controller tick/queue shape. Runs as a plain thread
    (its trigger is a metrics tick, not an object event — there is no
    informer to register)."""

    def __init__(self, driver, group: Optional[PartitionGroup] = None,
                 policy: Optional[RebalancePolicy] = None,
                 interval_s: float = 0.5):
        self.driver = driver
        self.group = group or PartitionGroup()
        self.policy = policy or RebalancePolicy()
        self.interval_s = float(interval_s)
        self.actions: List[dict] = []
        self._last: Optional[dict] = None
        self._hot_ticks = 0
        self._last_action_at = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    # -- one evaluation (callable directly from tests/harness) ---------
    def tick(self) -> Optional[dict]:
        with self._lock:
            return self._tick_locked()

    def _tick_locked(self) -> Optional[dict]:
        try:
            obs = self.driver.observe()
        except Exception as e:  # noqa: BLE001 — a dead fleet keeps
            _logger.warning("rebalancer observe failed: %s", e)
            return None
        try:
            self.driver.federate()
        except Exception:  # noqa: BLE001 — metrics must not block acts
            pass
        last = self._last
        self._last = obs
        if last is None:
            return None
        # per-tick write rates = ledger deltas (ledgers are cumulative;
        # a failover resets them, so clamp at zero)
        slot_rates = {
            s: max(0.0, obs["slot_writes"].get(s, 0)
                   - last["slot_writes"].get(s, 0))
            for s in obs["slot_writes"]}
        ns_rates = {
            n: max(0.0, obs["ns_writes"].get(n, 0)
                   - last["ns_writes"].get(n, 0))
            for n in obs["ns_writes"]}
        action = plan_rebalance(slot_rates, ns_rates, obs["topology"],
                                obs["dead"], self.policy, self.group)
        if action is None:
            self._hot_ticks = 0
            return None
        if action["op"] != "failover":
            self._hot_ticks += 1
            if self._hot_ticks < self.policy.sustain_ticks:
                return None
            if time.monotonic() - self._last_action_at \
                    < self.group.cooldown_s:
                return None
        try:
            report = self.driver.apply(action)
        except Exception as e:  # noqa: BLE001 — a failed migration
            # rolled back; try again next tick
            _logger.warning("rebalance %s failed: %s", action, e)
            return None
        self._hot_ticks = 0
        self._last_action_at = time.monotonic()
        done = {"action": action, "report": report,
                "at": time.monotonic()}
        self.actions.append(done)
        self._note_metrics(action)
        return done

    def _note_metrics(self, action: Dict[str, Any]) -> None:
        try:
            from kubernetes_tpu.metrics.autoscaler_metrics import (
                autoscaler_metrics,
            )

            m = autoscaler_metrics()
            if action["op"] == "buy":
                m.scaleups_total.inc(self.group.name, "rebalancer")
            elif action["op"] == "retire":
                m.scaledowns_total.inc(self.group.name)
        except Exception:  # noqa: BLE001 — metrics are best-effort
            pass

    # -- lifecycle ------------------------------------------------------
    def run(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="partition-rebalancer")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the loop must survive
                _logger.exception("rebalancer tick failed")

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)
