"""Cluster autoscaler: node groups, solver-simulated scale-up,
drain-based scale-down.

The elastic layer over the batched scheduling core: ``nodegroups``
holds the templates + the simulated cloud provisioner, ``simulator``
recasts upstream's per-pod scheduler simulation as virtual node
COLUMNS in the encoded pod×node planes (one batched solve per group
instead of one per pod), and ``controller`` is the leader-electable
RunOnce loop wiring trigger → expander → provision → drain.

Lazy exports (PEP 562): ``simulator`` transitively imports the jax
solver, and ``controller`` pulls the whole controllers package; the
eager surface is just ``nodegroups`` (api types only), so light
importers — ``harness/burst.py`` reading one annotation constant, the
REST harness's jax-free creator/apiserver children — pay for neither a
device backend nor the controller-manager import graph.
"""

from kubernetes_tpu.autoscaler.nodegroups import (
    NODE_GROUP_LABEL,
    SAFE_TO_EVICT_ANNOTATION,
    NodeGroup,
    NodeGroupRegistry,
    SimulatedProvisioner,
)

__all__ = [
    "ClusterAutoscaler",
    "EXPANDERS",
    "NODE_GROUP_LABEL",
    "NodeGroup",
    "NodeGroupRegistry",
    "SAFE_TO_EVICT_ANNOTATION",
    "ScaleUpOption",
    "ScaleUpPlan",
    "SimulatedProvisioner",
    "plan_scale_up",
    "pods_fit_elsewhere",
    "run_whatif",
    "scale_up_option",
]

_SIMULATOR_EXPORTS = (
    "EXPANDERS", "ScaleUpOption", "ScaleUpPlan", "plan_scale_up",
    "pods_fit_elsewhere", "run_whatif", "scale_up_option",
)


def __getattr__(name):
    if name == "ClusterAutoscaler":
        from kubernetes_tpu.autoscaler.controller import ClusterAutoscaler

        return ClusterAutoscaler
    if name in _SIMULATOR_EXPORTS:
        from kubernetes_tpu.autoscaler import simulator

        return getattr(simulator, name)
    raise AttributeError(name)
