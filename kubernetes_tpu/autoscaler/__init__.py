"""Cluster autoscaler: node groups, solver-simulated scale-up,
drain-based scale-down.

The elastic layer over the batched scheduling core: ``nodegroups``
holds the templates + the simulated cloud provisioner, ``simulator``
recasts upstream's per-pod scheduler simulation as virtual node
COLUMNS in the encoded pod×node planes (one batched solve per group
instead of one per pod), and ``controller`` is the leader-electable
RunOnce loop wiring trigger → expander → provision → drain.

Lazy exports (PEP 562): ``simulator`` transitively imports the jax
solver, and ``controller`` pulls the whole controllers package; the
eager surface is just ``nodegroups`` (api types only), so light
importers — ``harness/burst.py`` reading one annotation constant, the
REST harness's jax-free creator/apiserver children — pay for neither a
device backend nor the controller-manager import graph.
"""

from kubernetes_tpu.autoscaler.nodegroups import (
    NODE_GROUP_LABEL,
    SAFE_TO_EVICT_ANNOTATION,
    NodeGroup,
    NodeGroupRegistry,
    SimulatedProvisioner,
)

__all__ = [
    "ClusterAutoscaler",
    "EXPANDERS",
    "InprocElasticDriver",
    "NODE_GROUP_LABEL",
    "NodeGroup",
    "NodeGroupRegistry",
    "PartitionGroup",
    "PartitionRebalancer",
    "RebalancePolicy",
    "RestElasticDriver",
    "SAFE_TO_EVICT_ANNOTATION",
    "ScaleUpOption",
    "ScaleUpPlan",
    "SimulatedProvisioner",
    "plan_rebalance",
    "plan_scale_up",
    "pods_fit_elsewhere",
    "run_whatif",
    "scale_up_option",
]

_SIMULATOR_EXPORTS = (
    "EXPANDERS", "ScaleUpOption", "ScaleUpPlan", "plan_scale_up",
    "pods_fit_elsewhere", "run_whatif", "scale_up_option",
)

# control-plane elasticity (live partition resharding): jax-free, but
# lazy like the rest so light importers stay light
_PARTITION_EXPORTS = (
    "InprocElasticDriver", "PartitionGroup", "PartitionRebalancer",
    "RebalancePolicy", "RestElasticDriver", "plan_rebalance",
)


def __getattr__(name):
    if name == "ClusterAutoscaler":
        from kubernetes_tpu.autoscaler.controller import ClusterAutoscaler

        return ClusterAutoscaler
    if name in _SIMULATOR_EXPORTS:
        from kubernetes_tpu.autoscaler import simulator

        return getattr(simulator, name)
    if name in _PARTITION_EXPORTS:
        from kubernetes_tpu.autoscaler import partitions

        return getattr(partitions, name)
    raise AttributeError(name)
