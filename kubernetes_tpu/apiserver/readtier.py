"""Read-tier subsystem: horizontally-scalable watch replicas per
partition.

"Millions of users" is read-dominated — every kubelet, controller and
dashboard is a list+watch client, yet one partition process serves both
its authoritative writes AND its whole watch fan-out, so read load and
write load contend for the same dispatch threads (ROADMAP item 3; the
reference apiserver's watch-cache + reflector hierarchy is the
blueprint, and Pathways makes the same argument one layer down:
throughput is won by decoupling the serving fan-out from the
authoritative coordinator so neither waits on the other).

This module is the serving side of that split:

- ``ReplicationClient`` — subscribes to the owner's commit stream
  (``/api/v1/subscription``, rest.py): seeds from ``?snapshot=1`` via
  the silent ``adopt_objects`` channel (RVs preserved, no phantom
  events), then applies the live stream through
  ``ClusterStore.apply_replicated`` — the RV-preserving, per-object
  monotonic ingest whose equal-rv guard collapses resume overlap. The
  cursor is the max applied rv; a dropped connection resumes from it,
  and only a 410 (owner's cache AND WAL both compacted past the
  cursor) forces a reseed.
- ``FenceStateMachine`` — the staleness contract (PR 8 freshness SLI
  layer): replication lag per applied batch feeds a per-replica
  ``replication_lag_seconds`` histogram and this hysteresis machine. A
  replica past its lag budget for ``trip_after`` consecutive batches
  self-fences (server answers reads 503 + X-Replica-Fenced, sheds live
  watch streams; clients re-route, relist confined to THIS replica);
  ``clear_after`` consecutive batches under half the budget unfence it.
- ``ReadReplica`` — mirror ``ClusterStore`` + a ``read_only``
  ``APIServer`` serving lists from its own pre-encoded caches and
  watches from its own dispatch threads, fed by a ReplicationClient
  wired into the server's fence flag. Replicas are advertised in the
  ``PartitionTopology`` doc (``replicas`` field) so
  ``RestClusterClient`` routes reads to them while writes still hit
  the owner.

Loss model: replica loss costs a relist on that replica's clients
only; owner restart replays the missed window from the owner's WAL
(``attach_wal(..., preserve_log=True)``) so live replicas resubscribe
from their cursor with no reseed. Fleet-wide zero lost events is the
acceptance bar (harness/watchherd.py proves it with a differential
replicas-off arm held event-identical).
"""

from __future__ import annotations

import json
import socket
import threading
import time
from http.client import HTTPConnection
from typing import Any, Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.api.serialization import from_wire
from kubernetes_tpu.apiserver.rest import APIServer
from kubernetes_tpu.apiserver.store import (
    DELETED,
    MODIFIED,
    ClusterStore,
    Event,
)

__all__ = [
    "FenceStateMachine",
    "ReplicationClient",
    "ReadReplica",
]

# staleness contract defaults: a replica more than half a second behind
# its owner for 3 consecutive batches is serving history, not state —
# fence it. Unfencing needs sustained headroom (half the budget) so a
# replica oscillating at the budget edge doesn't flap client routing.
DEFAULT_LAG_BUDGET_S = 0.5
FENCE_TRIP_AFTER = 3
FENCE_CLEAR_AFTER = 5


class FenceStateMachine:
    """Pure hysteresis over replication-lag samples.

    ``observe(lag_s)`` returns ``True`` on the fence transition,
    ``False`` on the unfence transition, ``None`` otherwise — the
    caller (ReplicationClient) maps transitions onto the server's
    ``fenced`` event. Tripping takes ``trip_after`` CONSECUTIVE
    over-budget samples (one slow batch is a scheduling hiccup, not
    staleness); clearing takes ``clear_after`` consecutive samples
    under ``budget/2`` (recovering to just-under-budget still means
    one bad batch re-fences — demand real headroom before taking
    client traffic back)."""

    def __init__(self, lag_budget_s: float = DEFAULT_LAG_BUDGET_S,
                 trip_after: int = FENCE_TRIP_AFTER,
                 clear_after: int = FENCE_CLEAR_AFTER):
        self.lag_budget_s = float(lag_budget_s)
        self.trip_after = max(1, int(trip_after))
        self.clear_after = max(1, int(clear_after))
        self.fenced = False
        self.fences = 0          # lifetime fence transitions
        self._over = 0
        self._under = 0

    def observe(self, lag_s: float) -> Optional[bool]:
        if not self.fenced:
            if lag_s > self.lag_budget_s:
                self._over += 1
                if self._over >= self.trip_after:
                    self.fenced = True
                    self.fences += 1
                    self._under = 0
                    return True
            else:
                self._over = 0
            return None
        if lag_s <= self.lag_budget_s / 2.0:
            self._under += 1
            if self._under >= self.clear_after:
                self.fenced = False
                self._over = 0
                return False
        else:
            self._under = 0
        return None


def _parse_frame(line: bytes, known_kinds) -> Optional[Event]:
    """One subscription NDJSON line -> an Event for apply_replicated.
    Live frames carry the full object; WAL-replayed deletes carry a
    key-only stub — synthesize metadata so the mirror can pop and
    re-announce the stored body at the delete's revision."""
    frame = json.loads(line)
    kind = frame.get("kind")
    rv = int(frame.get("rv") or 0)
    etype = frame.get("type") or MODIFIED
    ts = float(frame.get("commitTs") or 0.0)
    if frame.get("object") is not None:
        obj = from_wire(frame["object"], kind)
    elif frame.get("key") is not None:
        ns, name = frame["key"]
        obj = from_wire({"kind": kind, "metadata": {
            "namespace": ns or "", "name": name,
            "resourceVersion": str(rv)}}, kind)
    else:
        return None
    if known_kinds is not None and kind not in known_kinds:
        return None
    return Event(etype, kind, obj, ts=ts, origin="owner")


class ReplicationClient:
    """Owner commit stream -> mirror store, with cursor resume.

    Seed: ``GET /api/v1/subscription?snapshot=1`` — a leading
    ``{"rv": R}`` line (captured before any kind is listed), then
    per-kind object batches adopted silently (``adopt_objects``: RVs
    preserved, no watch events — replica clients list first, they must
    not see a phantom ADDED storm). Cursor starts at R.

    Stream: ``GET /api/v1/subscription?resourceVersion=cursor`` —
    NDJSON frames applied via ``apply_replicated`` (RV-preserving,
    per-object monotonic, DISPATCHED: replica watch clients see the
    owner's history verbatim, commit stamps included). Cursor advances
    to the max applied rv, so a dropped connection resumes exactly
    where the mirror left off (counted in ``resumes``); a 410 means
    the owner compacted past the cursor and the mirror reseeds
    (counted in ``reseeds`` — this is the only path that costs the
    replica's clients a relist).

    Lag: ``now - commitTs`` per applied frame feeds the per-replica
    ``replication_lag_seconds`` histogram and the fence machine;
    fence transitions invoke ``fence_cb(fenced_bool)``.
    ``apply_delay`` is the chaos hook (tools/chaos_matrix.py lag-fence
    cell): sleeping before each apply manufactures real lag without
    touching the wire."""

    def __init__(self, owner_url: str, store: ClusterStore,
                 replica_id: str = "r0",
                 lag_budget_s: float = DEFAULT_LAG_BUDGET_S,
                 fence_cb: Optional[Callable[[bool], None]] = None,
                 apply_delay: float = 0.0,
                 token: str = ""):
        host_port = owner_url.rstrip("/").split("//", 1)[-1]
        host, _, port = host_port.partition(":")
        self._host, self._port = host, int(port or 80)
        self.store = store
        self.replica_id = replica_id
        self.fence = FenceStateMachine(lag_budget_s)
        self.fence_cb = fence_cb
        self.apply_delay = float(apply_delay)
        self.token = token
        self.cursor: Optional[int] = None
        self.events_applied = 0
        self.events_seen = 0
        self.resumes = 0
        self.reseeds = 0
        self.last_lag_s = 0.0
        self.max_lag_s = 0.0
        self.seeded = threading.Event()
        self._stop = threading.Event()
        self._conn: Optional[HTTPConnection] = None
        self._thread: Optional[threading.Thread] = None
        from kubernetes_tpu.metrics.freshness_metrics import (
            freshness_metrics,
        )

        self._lag_hist = freshness_metrics().replication_lag_seconds

    # -- lifecycle ----------------------------------------------------
    def start(self) -> "ReplicationClient":
        self._thread = threading.Thread(
            target=self._run, name=f"replication-{self.replica_id}",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        conn = self._conn
        if conn is not None:
            # force the blocked readline() home (the _sa_watch rule:
            # shutdown, not close — close() wants the reader's lock)
            sock = getattr(conn, "sock", None)
            if sock is not None:
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
            try:
                conn.close()
            except OSError:
                pass
        t = self._thread
        if t is not None:
            t.join(timeout=5)

    # -- wire ---------------------------------------------------------
    def _open(self, path: str):
        conn = HTTPConnection(self._host, self._port, timeout=30)
        headers = {}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        conn.request("GET", path, headers=headers)
        self._conn = conn
        return conn, conn.getresponse()

    def _seed(self) -> bool:
        try:
            conn, resp = self._open("/api/v1/subscription?snapshot=1")
        except OSError:
            return False
        try:
            if resp.status != 200:
                resp.read()
                return False
            head = resp.readline()
            if not head:
                return False
            rv0 = int(json.loads(head)["rv"])
            while True:
                line = resp.readline()
                if not line or line.strip() == b"":
                    break
                batch = json.loads(line)
                objs = [from_wire(w, batch["kind"])
                        for w in batch.get("objects") or ()]
                if objs:
                    self.store.adopt_objects(batch["kind"], objs)
        except (OSError, ValueError, KeyError, AttributeError):
            return False
        finally:
            try:
                conn.close()
            except Exception:
                pass
            self._conn = None
        self.cursor = rv0
        self.seeded.set()
        return True

    def _observe_lag(self, ts: float) -> None:
        if ts <= 0:
            return
        lag = max(0.0, time.time() - ts)
        self.last_lag_s = lag
        self.max_lag_s = max(self.max_lag_s, lag)
        self._lag_hist.observe(lag, self.replica_id)
        flip = self.fence.observe(lag)
        if flip is not None and self.fence_cb is not None:
            self.fence_cb(flip)

    def _stream_once(self) -> str:
        """One subscription attempt. Returns 'gone' (410 -> reseed),
        'retry' (transport drop -> resume from cursor) or 'stop'."""
        try:
            conn, resp = self._open(
                f"/api/v1/subscription?resourceVersion={self.cursor}")
        except OSError:
            return "retry"
        try:
            if resp.status == 410:
                resp.read()
                return "gone"
            if resp.status != 200:
                resp.read()
                return "retry"
            while not self._stop.is_set():
                line = resp.readline()
                if not line:
                    return "retry"
                line = line.strip()
                if not line:
                    continue
                self.events_seen += 1
                try:
                    e = _parse_frame(line, None)
                except (ValueError, KeyError, TypeError):
                    continue
                if e is None:
                    continue
                if self.apply_delay > 0:
                    time.sleep(self.apply_delay)
                applied = self.store.apply_replicated([e])
                self.events_applied += len(applied)
                rv = int(e.obj.metadata.resource_version or 0)
                if self.cursor is None or rv > self.cursor:
                    self.cursor = rv
                self._observe_lag(e.ts)
        except (OSError, ValueError, AttributeError):
            # a socket shut down mid-readline surfaces as ValueError /
            # AttributeError from http.client's chunk decoder, not
            # OSError — all of them mean "stream gone, resume"
            return "retry"
        finally:
            try:
                conn.close()
            except Exception:
                pass
            self._conn = None
        return "stop"

    def _run(self) -> None:
        backoff = 0.05
        while not self._stop.is_set():
            if self.cursor is None:
                if not self._seed():
                    self._stop.wait(backoff)
                    backoff = min(backoff * 2, 1.0)
                    continue
                backoff = 0.05
            outcome = self._stream_once()
            if outcome == "stop" or self._stop.is_set():
                return
            if outcome == "gone":
                # owner compacted past the cursor: full reseed — the
                # only path that costs this replica's clients a relist
                self.reseeds += 1
                self.cursor = None
            else:
                self.resumes += 1
            self._stop.wait(backoff)
            backoff = min(backoff * 2, 1.0)

    def stats(self) -> Dict[str, Any]:
        return {
            "replica": self.replica_id,
            "cursor": self.cursor,
            "events_seen": self.events_seen,
            "events_applied": self.events_applied,
            "resumes": self.resumes,
            "reseeds": self.reseeds,
            "fences": self.fence.fences,
            "fenced": self.fence.fenced,
            "last_lag_s": round(self.last_lag_s, 6),
            "max_lag_s": round(self.max_lag_s, 6),
        }


class ReadReplica:
    """One read replica: mirror store + read-only APIServer + the
    replication client that feeds it. Serves the owner's partition
    index (lists from its own pre-encoded caches, watches from its own
    dispatch threads); every mutating verb answers 503
    X-Replica-ReadOnly. The fence machine's transitions set/clear the
    server's ``fenced`` event — a fenced replica 503s reads
    (X-Replica-Fenced) and sheds live watch streams so clients
    re-route to a sibling or the owner."""

    def __init__(self, owner_url: str,
                 partition: Tuple[int, int] = (0, 1),
                 replica_id: str = "r0",
                 host: str = "127.0.0.1", port: int = 0,
                 lag_budget_s: float = DEFAULT_LAG_BUDGET_S,
                 apply_delay: float = 0.0,
                 tokens: Optional[Dict[str, str]] = None,
                 authorizer: Any = None,
                 token: str = ""):
        self.replica_id = replica_id
        self.store = ClusterStore()
        kwargs: Dict[str, Any] = dict(
            store=self.store, host=host, port=port,
            partition=tuple(partition), read_only=True,
            # replicas exist to absorb fan-out: no APF, no lane caps —
            # back-pressure belongs on the owner's write path
            flow_control=None, max_readonly_inflight=None,
            max_mutating_inflight=None,
        )
        if tokens is not None:
            kwargs["tokens"] = tokens
        if authorizer is not None:
            kwargs["authorizer"] = authorizer
        self.server = APIServer(**kwargs)
        self.repl = ReplicationClient(
            owner_url, self.store, replica_id=replica_id,
            lag_budget_s=lag_budget_s, apply_delay=apply_delay,
            fence_cb=self._on_fence, token=token)

    def _on_fence(self, fenced: bool) -> None:
        if fenced:
            self.server.fenced.set()
        else:
            self.server.fenced.clear()

    # -- lifecycle ----------------------------------------------------
    def start(self, seed_timeout: float = 10.0) -> "ReadReplica":
        self.server.start()
        self.repl.start()
        # serve no reads before the first seed: an empty mirror would
        # answer lists with rv=0 and every informer would relist
        self.repl.seeded.wait(seed_timeout)
        return self

    def stop(self) -> None:
        self.repl.stop()
        self.server.shutdown_server()

    def kill(self) -> None:
        """Hard kill (in-proc chaos): stop serving AND sever every
        live client connection, like a SIGKILLed process dropping its
        sockets — pooled keep-alive clients must see the failure, not
        keep being served by surviving handler threads."""
        self.repl.stop()
        self.server.shutdown_server()
        self.server.sever_connections()
        try:
            self.server.server_close()
        except OSError:
            pass

    @property
    def url(self) -> str:
        return self.server.url

    def stats(self) -> Dict[str, Any]:
        s = self.repl.stats()
        s["url"] = self.url
        s["store_rv"] = self.store.current_rv()
        return s


def advertise_replicas(topology, partition: int,
                       urls: List[str]):
    """Evolve a PartitionTopology with this partition's replica URLs
    (epoch bump — clients refresh and start routing reads)."""
    replicas = dict(topology.replicas)
    if urls:
        replicas[int(partition)] = tuple(u.rstrip("/") for u in urls)
    else:
        replicas.pop(int(partition), None)
    return topology.evolve(replicas=replicas)
