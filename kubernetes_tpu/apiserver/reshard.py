"""ReshardCoordinator: live partition migration over the REST fabric.

The multi-process half of the elastic control plane. The in-process
``PartitionedStore`` migrates slices under its own locks; a REAL
deployment runs one apiserver process per partition, so the same
freeze → copy → flip → evict protocol has to be driven over the wire
through each server's ``/debug/partition`` admin surface:

1. **freeze** the moving slots on their source servers (writes to the
   slice answer 429 + computed Retry-After through the APF envelope —
   clients pause, nothing is dropped);
2. **copy** the slice out (``slice`` op, RVs preserved) and **adopt**
   it into the destination (the silent placement channel: no watch
   events, WAL-logged for failover);
3. **flip**: install the successor topology (epoch + 1) — destinations
   FIRST (so the first server to answer the new epoch can serve it),
   sources second (ending their ownership while the freeze still
   covers the slice — there is never a moment with two owners), then
   bystanders;
4. **evict** the source copies after a short grace (an in-flight
   fan-in list that chose its partition set pre-flip still finds the
   objects; dict-keyed consumers collapse the transient duplicate).

Crash discipline (the chaos suite's subject): every step is
idempotent-or-rollbackable. A destination that dies mid-copy → the
coordinator unfreezes the sources and evicts any orphan copies
(rollback; the old topology never stopped being true). A source that
dies after the flip → the committed topology stands; ``resolve()``
pushes the max epoch everywhere and ``evict_unowned`` clears orphans
when the corpse restarts from its WAL. The routing table is therefore
never torn: ownership changes only at the flip, and the flip is a
single epoch-guarded document install per server.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.apiserver.partition import (
    PartitionTopology,
    slot_for,
)


class ReshardError(RuntimeError):
    """A migration step failed; the coordinator rolled back (or the
    failure happened after the flip, in which case the migration is
    COMMITTED and ``resolve()`` finishes the cleanup)."""

    def __init__(self, message: str, committed: bool = False):
        super().__init__(message)
        self.committed = committed


class ReshardCoordinator:
    """Drives slice migrations across a fleet of partition apiservers
    through a control-plane ``RestClusterClient``."""

    def __init__(self, client, freeze_eta: float = 5.0,
                 evict_grace_s: float = 0.25):
        self.client = client
        self.freeze_eta = float(freeze_eta)
        self.evict_grace_s = float(evict_grace_s)
        self.reports: List[dict] = []

    # -- admin plumbing ------------------------------------------------
    def _admin(self, partition: int, payload: dict) -> dict:
        code, resp = self.client._request(
            "POST", "/debug/partition", payload, body_binary=False,
            partition=partition)
        if code != 200:
            msg = resp.get("message") if isinstance(resp, dict) else resp
            raise ReshardError(
                f"partition {partition} admin op "
                f"{payload.get('op')!r} failed: HTTP {code} {msg}")
        return resp

    def _admin_get(self, partition: int) -> dict:
        code, resp = self.client._request(
            "GET", "/debug/partition", partition=partition)
        if code != 200:
            raise ReshardError(
                f"partition {partition} admin GET failed: HTTP {code}")
        return resp

    @staticmethod
    def _seam(name: str, t0: float, epoch: int, **attrs) -> None:
        """Record a first-class seam span into the fleet timeline
        (trace id ``seam:<epoch>``): the critical-path pass folds these
        windows into any sampled pod whose in-flight time overlaps
        them, so a queue.wait that straddles a freeze names the freeze
        instead of showing up as unattributed stall."""
        try:
            from kubernetes_tpu.observability import get_tracer

            get_tracer().record(name, t0, trace=f"seam:{epoch}",
                                **attrs)
        except Exception:  # noqa: BLE001 — tracing must not fail a flip
            pass

    def stats(self) -> List[dict]:
        """Best-effort per-partition admin stats (the rebalancer's
        load feed over REST). Dead partitions report ``alive: False``
        — the failover trigger."""
        out = []
        for p in range(len(self.client.partition_urls)):
            try:
                got = self._admin_get(p)
                got["alive"] = True
            except Exception as e:  # noqa: BLE001 — dead partition
                got = {"partition": p, "alive": False,
                       "error": str(e)[:200]}
            out.append(got)
        return out

    def fetch_topology(self) -> PartitionTopology:
        """The committed topology: max epoch across live endpoints
        (a partially-flipped fleet answers with the newest — epoch
        installs are monotonic, so max is the one that won)."""
        best: Optional[PartitionTopology] = None
        last_err: Optional[Exception] = None
        for p in range(len(self.client.partition_urls)):
            try:
                # probe, not a request: a dead endpoint (the failover
                # this fetch serves) must cost one refused connect, not
                # a full retry-backoff ladder inside the outage window
                code, doc = self.client._request(
                    "GET", "/api/v1/partitiontopology", partition=p,
                    retries=0)
            except Exception as e:  # noqa: BLE001 — dead endpoint
                last_err = e
                continue
            if code != 200 or "owner" not in doc:
                continue
            topo = PartitionTopology.from_dict(doc)
            if best is None or topo.epoch > best.epoch:
                best = topo
        if best is None:
            raise ReshardError(
                f"no endpoint served a live topology ({last_err})")
        return best

    def install_topology(self, topo: PartitionTopology,
                         order: Optional[List[int]] = None,
                         strict: bool = True) -> List[int]:
        """Install ``topo`` on every (listed) server, returning the
        indices that accepted. ``strict`` raises on the first failure
        (mid-migration flip); non-strict is resolve()'s best-effort."""
        doc = topo.to_dict()
        targets = order if order is not None \
            else list(range(len(topo.urls or self.client.partition_urls)))
        done: List[int] = []
        for p in targets:
            try:
                self._admin(p, {"op": "topology", "topology": doc})
                done.append(p)
            except Exception as e:  # noqa: BLE001
                if strict:
                    raise ReshardError(
                        f"topology install failed on partition {p}: {e}",
                        committed=bool(done)) from e
        return done

    # -- the protocol --------------------------------------------------
    def _freeze(self, by_src: Dict[int, List[int]], eta: float) -> None:
        for src, slots in by_src.items():
            self._admin(src, {"op": "freeze", "slots": slots,
                              "eta": eta})

    def _unfreeze(self, by_src: Dict[int, List[int]]) -> None:
        for src, slots in by_src.items():
            try:
                self._admin(src, {"op": "unfreeze", "slots": slots})
            except Exception:  # noqa: BLE001 — freeze auto-thaws at eta
                pass

    def _verify_frozen(self, by_src: Dict[int, List[int]]) -> None:
        """Pre-flip guard: every moving slot must STILL be frozen on
        its source. A copy that outlived the freeze budget thawed
        writers back into the slice — flipping now would lose whatever
        they wrote since the copy. Abort (rollback) instead: the old
        topology never stopped being true, and the caller retries with
        a bigger budget."""
        for src, slots in by_src.items():
            got = self._admin_get(src)
            frozen_now = {int(s) for s in got.get("frozen") or ()}
            missing = [s for s in slots if s not in frozen_now]
            if missing:
                raise ReshardError(
                    f"freeze expired on partition {src} slots "
                    f"{missing} before the flip — aborting (copy "
                    f"outlived the freeze budget; retry with a "
                    f"larger freeze_eta)")

    def _copy(self, topo: PartitionTopology,
              new_topo: PartitionTopology,
              by_src: Dict[int, List[int]],
              namespace: Optional[str] = None,
              kill_hook=None) -> Tuple[int, Dict[int, Dict[str, list]],
                                       Dict[int, Dict[str, list]]]:
        """slice + adopt. Returns (moved, adopted_by_dest (wire),
        evict_keys_by_src). Slot membership is judged under the NEW
        topology's spread — a split cuts exactly where the new routing
        will read."""
        moved = 0
        adopted: Dict[int, Dict[str, list]] = {}
        evict: Dict[int, Dict[str, list]] = {}
        for src, slots in by_src.items():
            got = self._admin(src, {
                "op": "slice", "slots": slots,
                "spread": sorted(new_topo.spread),
                "slot_count": new_topo.slots,
                "namespace": namespace,
            })
            for kind, wires in (got.get("objects") or {}).items():
                for w in wires:
                    meta = w.get("metadata") or {}
                    ns, name = meta.get("namespace"), meta.get("name")
                    dest = new_topo.partition_of(kind, ns, name)
                    if dest == src:
                        continue
                    adopted.setdefault(dest, {}).setdefault(
                        kind, []).append(w)
                    evict.setdefault(src, {}).setdefault(
                        kind, []).append([ns, name])
                    moved += 1
        if kill_hook is not None:
            kill_hook("copied")   # chaos seam: crash after copy
        for dest, objmap in adopted.items():
            self._admin(dest, {"op": "adopt", "objects": objmap})
        return moved, adopted, evict

    def _rollback(self, by_src: Dict[int, List[int]],
                  adopted: Dict[int, Dict[str, list]]) -> None:
        """Undo a failed (pre-flip) migration: drop any orphan copies
        from reachable destinations, thaw the sources. The old
        topology never stopped being the committed one."""
        for dest, objmap in adopted.items():
            keys = {kind: [[w["metadata"].get("namespace"),
                            w["metadata"].get("name")] for w in ws]
                    for kind, ws in objmap.items()}
            try:
                self._admin(dest, {"op": "evict", "keys": keys})
            except Exception:  # noqa: BLE001 — dead dest: its WAL
                pass           # restart runs evict_unowned via resolve()
        self._unfreeze(by_src)

    def _run_migration(self, topo: PartitionTopology,
                       new_topo: PartitionTopology,
                       by_src: Dict[int, List[int]],
                       reason: str,
                       namespace: Optional[str] = None,
                       freeze_eta: Optional[float] = None,
                       kill_hook=None) -> dict:
        eta = freeze_eta if freeze_eta is not None else self.freeze_eta
        t0 = time.monotonic()
        self._freeze(by_src, eta)
        adopted: Dict[int, Dict[str, list]] = {}
        try:
            moved, adopted, evict = self._copy(
                topo, new_topo, by_src, namespace=namespace,
                kill_hook=kill_hook)
            # FLIP: destinations first, sources second (their freeze
            # still covers the slice — no double-ownership window),
            # bystanders last
            all_parts = list(range(len(new_topo.urls
                                       or self.client.partition_urls)))
            dests = [p for p in adopted if p not in by_src]
            srcs = list(by_src)
            rest = [p for p in all_parts
                    if p not in dests and p not in srcs]
            if kill_hook is not None:
                kill_hook("pre_flip")   # chaos seam: crash before flip
            self._verify_frozen(by_src)
            t_flip = time.monotonic()
            self.install_topology(new_topo, order=dests + srcs + rest)
            self._seam("reshard.flip", t_flip, new_topo.epoch,
                       reason=reason)
        except ReshardError as e:
            if not getattr(e, "committed", False):
                self._rollback(by_src, adopted)
                self._seam("reshard.rollback", t0, new_topo.epoch,
                           reason=reason)
                raise
            # flip partially landed: the new epoch exists somewhere —
            # the migration IS committed; finish via resolve()
            self.resolve(new_topo)
            raise
        except Exception:
            self._rollback(by_src, adopted)
            self._seam("reshard.rollback", t0, new_topo.epoch,
                       reason=reason)
            raise
        frozen_ms = (time.monotonic() - t0) * 1000.0
        self._unfreeze(by_src)   # install already dropped non-owned
        self._seam("reshard.freeze", t0, new_topo.epoch, reason=reason,
                   frozen_ms=round(frozen_ms, 3))
        if self.evict_grace_s > 0 and evict:
            time.sleep(self.evict_grace_s)
        evict_failures = {}
        for src, keys in evict.items():
            try:
                self._admin(src, {"op": "evict", "keys": keys})
            except Exception as e:  # noqa: BLE001 — resolve() can
                evict_failures[src] = f"{type(e).__name__}: {e}"[:300]
        # hand the coordinator's own client the new routing NOW (its
        # poller would also catch it; this avoids one stale round)
        try:
            self.client.apply_topology(new_topo)
        except Exception:  # noqa: BLE001
            pass
        report = {
            "reason": reason,
            "epoch": new_topo.epoch,
            "moved_objects": moved,
            "frozen_slots": sorted(s for ss in by_src.values()
                                   for s in ss),
            "frozen_ms": round(frozen_ms, 3),
        }
        if evict_failures:
            report["evict_failed"] = evict_failures
        self.reports.append(report)
        return report

    # -- operations ----------------------------------------------------
    def move_slots(self, assignments: Dict[int, int],
                   freeze_eta: Optional[float] = None,
                   kill_hook=None) -> dict:
        """MOVE hash slots to new owners ({slot: dest})."""
        topo = self.fetch_topology()
        owner = list(topo.owner)
        by_src: Dict[int, List[int]] = {}
        for slot, dest in assignments.items():
            if owner[slot] != dest:
                by_src.setdefault(owner[slot], []).append(int(slot))
                owner[slot] = int(dest)
        if not by_src:
            return {"reason": "move", "epoch": topo.epoch,
                    "moved_objects": 0, "frozen_slots": [],
                    "frozen_ms": 0.0}
        return self._run_migration(
            topo, topo.evolve(owner=owner), by_src, "move",
            freeze_eta=freeze_eta, kill_hook=kill_hook)

    def spread_namespace(self, namespace: str,
                         freeze_eta: Optional[float] = None,
                         kill_hook=None) -> dict:
        """SPLIT a hot namespace: its pods re-slot by (namespace,
        name), fanning one tenant across every partition."""
        topo = self.fetch_topology()
        if namespace in topo.spread:
            return {"reason": "split", "epoch": topo.epoch,
                    "moved_objects": 0, "frozen_slots": [],
                    "frozen_ms": 0.0}
        old_slot = topo.slot_of("Pod", namespace, None)
        src = topo.owner[old_slot]
        new_topo = topo.evolve(spread=topo.spread | {namespace})
        # the frozen slice is the namespace's OLD slot; the copy is
        # namespace-scoped and judged under the NEW spread
        return self._run_split(topo, new_topo, src, old_slot,
                               namespace, freeze_eta, kill_hook)

    def _run_split(self, topo, new_topo, src, old_slot, namespace,
                   freeze_eta, kill_hook) -> dict:
        """Split copy: the slice is 'every pod of the namespace whose
        NEW slot leaves src' — slice op scoped by namespace across all
        slots (the namespace's objects all live on src today)."""
        eta = freeze_eta if freeze_eta is not None else self.freeze_eta
        t0 = time.monotonic()
        by_src = {src: [old_slot]}
        self._freeze(by_src, eta)
        adopted: Dict[int, Dict[str, list]] = {}
        try:
            got = self._admin(src, {
                "op": "slice", "slots": list(range(new_topo.slots)),
                "spread": sorted(new_topo.spread),
                "slot_count": new_topo.slots,
                "namespace": namespace,
            })
            moved = 0
            evict: Dict[str, list] = {}
            for kind, wires in (got.get("objects") or {}).items():
                for w in wires:
                    meta = w.get("metadata") or {}
                    ns, name = meta.get("namespace"), meta.get("name")
                    dest = new_topo.partition_of(kind, ns, name)
                    if dest == src:
                        continue
                    adopted.setdefault(dest, {}).setdefault(
                        kind, []).append(w)
                    evict.setdefault(kind, []).append([ns, name])
                    moved += 1
            if kill_hook is not None:
                kill_hook("copied")
            for dest, objmap in adopted.items():
                self._admin(dest, {"op": "adopt", "objects": objmap})
            all_parts = list(range(len(new_topo.urls
                                       or self.client.partition_urls)))
            dests = [p for p in adopted if p != src]
            rest = [p for p in all_parts
                    if p not in dests and p != src]
            if kill_hook is not None:
                kill_hook("pre_flip")
            self._verify_frozen(by_src)
            t_flip = time.monotonic()
            self.install_topology(new_topo, order=dests + [src] + rest)
            self._seam("reshard.flip", t_flip, new_topo.epoch,
                       reason="split")
        except ReshardError as e:
            if not getattr(e, "committed", False):
                self._rollback(by_src, adopted)
                self._seam("reshard.rollback", t0, new_topo.epoch,
                           reason="split")
                raise
            self.resolve(new_topo)
            raise
        except Exception:
            self._rollback(by_src, adopted)
            self._seam("reshard.rollback", t0, new_topo.epoch,
                       reason="split")
            raise
        frozen_ms = (time.monotonic() - t0) * 1000.0
        self._unfreeze(by_src)
        self._seam("reshard.freeze", t0, new_topo.epoch,
                   reason="split", frozen_ms=round(frozen_ms, 3))
        if self.evict_grace_s > 0 and evict:
            time.sleep(self.evict_grace_s)
        evict_failed = ""
        if evict:
            try:
                self._admin(src, {"op": "evict", "keys": evict})
            except Exception as e:  # noqa: BLE001 — resolve() can
                evict_failed = f"{type(e).__name__}: {e}"[:300]
        try:
            self.client.apply_topology(new_topo)
        except Exception:  # noqa: BLE001
            pass
        report = {"reason": "split", "epoch": new_topo.epoch,
                  "moved_objects": moved,
                  "frozen_slots": [old_slot],
                  "frozen_ms": round(frozen_ms, 3),
                  "namespace": namespace}
        if evict_failed:
            report["evict_failed"] = evict_failed
        self.reports.append(report)
        return report

    def split_to(self, new_url: str,
                 slots: Optional[List[int]] = None,
                 freeze_eta: Optional[float] = None,
                 kill_hook=None) -> dict:
        """Grow the fleet: a freshly-booted partition server at
        ``new_url`` joins the topology and receives ``slots`` (default:
        an even share, taken round-robin from the most-loaded owners).
        The buy half is the control-plane autoscaler's job; this is
        the rebalance half."""
        topo = self.fetch_topology()
        urls = list(topo.urls or self.client.partition_urls)
        new_index = len(urls)
        urls.append(new_url.rstrip("/"))
        grown = topo.evolve(partitions=new_index + 1, urls=urls)
        # the coordinator's own client must learn the new endpoint
        # BEFORE it can drive it (the grown topology assigns it no
        # slots yet, so routing is unchanged — only the pool exists)
        self.client.apply_topology(grown, replumb=False)
        # push the grown (still slot-less) topology so every server —
        # including the new one — knows the fleet shape first
        self.install_topology(grown, order=[new_index] + list(
            range(new_index)))
        if slots is None:
            counts: Dict[int, int] = {}
            for o in grown.owner:
                counts[o] = counts.get(o, 0) + 1
            want = grown.slots // (new_index + 1)
            slots = []
            owners = sorted(counts, key=counts.get, reverse=True)
            per = {o: grown.slots_of_partition(o) for o in owners}
            while len(slots) < want:
                progressed = False
                for o in owners:
                    if per[o] and counts[o] > want:
                        slots.append(per[o].pop())
                        counts[o] -= 1
                        progressed = True
                        if len(slots) >= want:
                            break
                if not progressed:
                    break
        report = self.move_slots({s: new_index for s in slots},
                                 freeze_eta=freeze_eta,
                                 kill_hook=kill_hook)
        report["reason"] = "split_partition"
        report["new_partition"] = new_index
        return report

    def retire(self, index: int,
               freeze_eta: Optional[float] = None) -> dict:
        """MERGE a partition away: its slots drain to the survivors
        and it is marked retired (traffic-free; safe to tear down)."""
        topo = self.fetch_topology()
        live = [p for p in range(topo.partitions)
                if p != index and p not in topo.retired]
        if not live:
            raise ReshardError("cannot retire the last live partition")
        owner = list(topo.owner)
        moving = [s for s, o in enumerate(owner) if o == index]
        for k, slot in enumerate(moving):
            owner[slot] = live[k % len(live)]
        new_topo = topo.evolve(owner=owner,
                               retired=topo.retired | {index})
        report = self._run_migration(
            topo, new_topo, {index: moving}, "merge",
            freeze_eta=freeze_eta)
        return report

    # -- failure handling ----------------------------------------------
    def resolve(self, topo: Optional[PartitionTopology] = None) -> dict:
        """Converge after a failure: push the committed (max-epoch)
        topology to every reachable server and clear orphan copies
        (``evict_unowned``). Idempotent; safe to call any time."""
        topo = topo or self.fetch_topology()
        installed = self.install_topology(topo, strict=False)
        evicted: Dict[int, dict] = {}
        for p in range(len(topo.urls or self.client.partition_urls)):
            try:
                got = self._admin(p, {"op": "evict_unowned"})
                if got.get("evicted"):
                    evicted[p] = got["evicted"]
            except Exception:  # noqa: BLE001 — dead partition
                pass
        try:
            self.client.apply_topology(topo)
        except Exception:  # noqa: BLE001
            pass
        return {"epoch": topo.epoch, "installed": installed,
                "evicted": evicted}

    def reroute_after_restart(self, index: int, new_url: str) -> dict:
        """FAILOVER epilogue: a dead partition came back (WAL-restored)
        at ``new_url``. Bump the epoch with the updated endpoint so
        every client re-points its streams — their known maps carry
        them across the gap with at most a diff of THAT partition's
        slice."""
        topo = self.fetch_topology()
        urls = list(topo.urls or self.client.partition_urls)
        urls[index] = new_url.rstrip("/")
        new_topo = topo.evolve(urls=urls)
        t0 = time.monotonic()
        # re-point the coordinator's OWN routing first (routing-only):
        # the install below reaches the restarted server through its
        # new endpoint instead of the corpse's
        self.client.apply_topology(new_topo, replumb=False)
        self.install_topology(new_topo, strict=False)
        got = self.resolve(new_topo)
        self._seam("reshard.reroute", t0, new_topo.epoch,
                   reason="failover", partition=index)
        report = {"reason": "failover", "partition": index,
                  "epoch": new_topo.epoch, "resolve": got}
        self.reports.append(report)
        return report
