"""API Priority & Fairness for the REST fabric (KEP-1040; reference
``staging/src/k8s.io/apiserver/pkg/util/flowcontrol`` + its ``fairqueuing
/queueset``).

The raw readonly/mutating max-in-flight semaphores protect the server
but not the *tenants*: one hot client doing list storms or bulk-verb
abuse fills both lanes and starves the scheduler's bind traffic. This
module replaces the lanes as the admission decision for every
non-exempt request:

- **FlowSchemas** match requests (identity/groups/verb/resource/
  namespace, precedence-ordered with a catch-all) and route them to a
  priority level, deriving a **flow distinguisher** (the tenant key).
- **PriorityLevels** (system/control-plane, workload tenants,
  best-effort, plus a true ``exempt`` level) each hold an assured seat
  budget derived from the legacy lane budgets — shares of
  ``readonly + mutating`` total, so deploy-time tuning carries over.
- Each limited level runs a **QueueSet**: N bounded FIFO queues,
  **shuffle-sharded** flow assignment (hash of the distinguisher deals
  ``hand_size`` candidate queues; the request joins the shortest), and
  fair dispatch across queues by least-virtual-work — a noisy flow
  fills only its own hand of queues and never more than its fair share
  of seats.
- **Width estimation**: a request occupies ``seats >= 1`` while
  executing. Bulk ``{Kind}List`` verbs declare their item count
  (``X-Kubernetes-Request-Items``, the client-side analog of charging
  the token bucket per object) and consume proportional seats —
  batching must not launder concurrency. Expensive list GETs are
  widened by an EWMA of recently served list sizes, and watch
  initialization (the reconnect-herd replay burst) charges
  ``watch_init_seats`` released as soon as the stream attaches.
- On queue-full, queue-deadline, or **overload shed** (aggregate queued
  seat demand beyond ``shed_factor`` of total capacity: sheddable
  levels reject instead of queueing, protecting the control-plane
  level's bind/status traffic) the request is rejected 429 with an
  honest computed ``Retry-After`` (queued seats x average execution
  seconds / capacity — the level's actual drain time, never a
  hard-coded constant).

``FlowController.snapshot()`` feeds the ``/debug/apf`` introspection
endpoint and the chaos-suite invariants (no starved flow, exempt
always served, per-object rate equivalence for bulk verbs).
"""

from __future__ import annotations

import collections
import hashlib
import math
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.apiserver.faults import api_segments, namespace_of

__all__ = [
    "FlowControlConfig", "FlowController", "FlowSchema", "LaneStats",
    "PriorityLevelSpec", "Rejected", "Ticket", "WidthEstimator",
    "default_config", "is_collection_path", "namespace_of",
    "shuffle_shard_hand",
]


class Rejected(Exception):
    """Admission refused. Carries everything the 429 response needs."""

    def __init__(self, level: str, schema: str, reason: str,
                 retry_after: float):
        super().__init__(
            f"priority level {level!r} rejected request ({reason}); "
            f"retry after {retry_after:.3f}s")
        self.level = level
        self.schema = schema
        self.reason = reason
        self.retry_after = retry_after


def shuffle_shard_hand(flow_hash: int, deck_size: int,
                       hand_size: int) -> List[int]:
    """Deal ``hand_size`` DISTINCT queue indices out of ``deck_size``
    from the flow's hash (reference ``shufflesharding.Dealer``): two
    tenants share a whole hand only with probability ~(hand/deck)^hand,
    so a noisy flow drowning its own queues leaves every other flow a
    clean queue with high probability."""
    hand_size = max(1, min(hand_size, deck_size))
    remaining = list(range(deck_size))
    cards: List[int] = []
    h = flow_hash
    for i in range(hand_size):
        h, r = divmod(h, deck_size - i)
        cards.append(remaining.pop(r))
    return cards


def _flow_hash(level: str, flow_key: str) -> int:
    digest = hashlib.sha256(f"{level}\x00{flow_key}".encode()).digest()
    return int.from_bytes(digest[:8], "big")


# ---------------------------------------------------------------------------
# configuration


class FlowSchema:
    """Request classifier: ``match(user, groups, verb, resource, ns)``
    routes to ``priority_level`` with a flow distinguisher derived per
    ``distinguisher`` ("user" | "namespace" | "none"). Lower precedence
    wins, like the reference's matchingPrecedence."""

    def __init__(self, name: str, precedence: int, priority_level: str,
                 match: Optional[Callable[..., bool]] = None,
                 distinguisher: str = "user"):
        self.name = name
        self.precedence = int(precedence)
        self.priority_level = priority_level
        self._match = match
        self.distinguisher = distinguisher

    def matches(self, user: str, groups: Sequence[str], verb: str,
                resource: str, namespace: str) -> bool:
        if self._match is None:
            return True
        return bool(self._match(user, groups, verb, resource, namespace))

    def flow_key(self, user: str, namespace: str, flow_id: str) -> str:
        if self.distinguisher == "user":
            base = user
        elif self.distinguisher == "namespace":
            base = namespace
        else:
            base = ""
        # flow_id refines the flow within an identity (several tenants
        # behind one loopback identity in the bench harness). The server
        # forwards the X-Flow-Id header ONLY from control-plane/loopback
        # identities — an untrusted distinguisher would let one tenant
        # mint a flow per request and defeat shuffle-shard isolation.
        return f"{base}|{flow_id}" if flow_id else base


class PriorityLevelSpec:
    def __init__(self, name: str, shares: int = 10, queues: int = 8,
                 queue_length: int = 64, hand_size: int = 4,
                 sheddable: bool = False, exempt: bool = False):
        self.name = name
        self.shares = int(shares)
        self.queues = int(queues)
        self.queue_length = int(queue_length)
        self.hand_size = int(hand_size)
        self.sheddable = bool(sheddable)
        self.exempt = bool(exempt)


class FlowControlConfig:
    def __init__(self, levels: Sequence[PriorityLevelSpec],
                 schemas: Sequence[FlowSchema],
                 total_seats: int = 600,
                 queue_wait_s: float = 1.0,
                 shed_factor: float = 0.8):
        self.levels = list(levels)
        self.schemas = sorted(schemas, key=lambda s: s.precedence)
        self.total_seats = int(total_seats)
        self.queue_wait_s = float(queue_wait_s)
        self.shed_factor = float(shed_factor)
        by_level = {lv.name for lv in levels}
        for s in self.schemas:
            if s.priority_level not in by_level:
                raise ValueError(
                    f"schema {s.name!r} routes to unknown level "
                    f"{s.priority_level!r}")


def _is_control_plane(user, groups, verb, resource, ns) -> bool:
    return user.startswith(("system:kube-", "system:node:"))


def _is_master(user, groups, verb, resource, ns) -> bool:
    return "system:masters" in groups


def _is_authenticated(user, groups, verb, resource, ns) -> bool:
    return bool(user) and user != "system:anonymous" \
        and not user.startswith("token:")


def default_config(max_readonly_inflight: Optional[int],
                   max_mutating_inflight: Optional[int],
                   queue_wait_s: float = 1.0) -> FlowControlConfig:
    """The default tiering, with total seats derived from the legacy
    lane budgets (reference defaults 400 readonly + 200 mutating):

    - ``exempt``     — system:masters (the reference's exempt schema):
      cluster-admin traffic is never queued or charged;
    - ``system``     — control-plane identities (scheduler binds/status,
      kubelets, controller-manager): the traffic the headline metric
      rides on; protected, never shed;
    - ``workload``   — authenticated tenants, one flow per identity;
    - ``best-effort``— the catch-all (anonymous, unknown tokens).
    """
    total = (max_readonly_inflight or 400) + (max_mutating_inflight or 200)
    levels = [
        PriorityLevelSpec("exempt", exempt=True),
        PriorityLevelSpec("system", shares=40, queues=8, hand_size=4,
                          queue_length=128, sheddable=False),
        PriorityLevelSpec("workload", shares=40, queues=16, hand_size=4,
                          queue_length=64, sheddable=True),
        PriorityLevelSpec("best-effort", shares=20, queues=8, hand_size=4,
                          queue_length=32, sheddable=True),
    ]
    schemas = [
        FlowSchema("exempt", 0, "exempt", _is_master,
                   distinguisher="none"),
        FlowSchema("system-control-plane", 10, "system",
                   _is_control_plane),
        FlowSchema("workload-tenants", 20, "workload", _is_authenticated),
        FlowSchema("catch-all", 10_000, "best-effort"),
    ]
    return FlowControlConfig(levels, schemas, total_seats=total,
                             queue_wait_s=queue_wait_s)


# ---------------------------------------------------------------------------
# width estimation


class WidthEstimator:
    """Seats a request occupies while executing. Everything is 1 except
    the request shapes whose cost is proportional to object count:
    bulk verbs (declared item count), list GETs (EWMA of recently
    served list sizes per resource), watch initialization, and very
    large undeclared bodies (content-length fallback)."""

    def __init__(self, items_per_seat: int = 100,
                 list_objects_per_seat: int = 500,
                 bytes_per_seat: int = 256 * 1024,
                 bulk_item_bytes: int = 256,
                 max_seats: int = 10, watch_init_seats: int = 2):
        self.items_per_seat = int(items_per_seat)
        self.list_objects_per_seat = int(list_objects_per_seat)
        self.bytes_per_seat = int(bytes_per_seat)
        self.bulk_item_bytes = int(bulk_item_bytes)
        self.max_seats = int(max_seats)
        self.watch_init_seats = int(watch_init_seats)
        self._list_sizes: Dict[str, float] = {}

    def note_list_size(self, resource: str, n: int) -> None:
        """EWMA of served list sizes, fed by the server after every
        list response — the width of the NEXT list of this resource.
        Unlocked: float stores are GIL-atomic and this is an estimate."""
        prev = self._list_sizes.get(resource)
        self._list_sizes[resource] = float(n) if prev is None \
            else 0.7 * prev + 0.3 * n

    def estimate(self, verb: str, resource: str, is_collection_get: bool,
                 is_watch: bool, items_hint: int,
                 content_length: int,
                 is_collection_mutation: bool = False) -> int:
        if is_watch:
            return self.watch_init_seats
        if is_collection_mutation and content_length > 0:
            # bulk mutations price by the DECLARED item count, floored
            # by a conservative per-item byte estimate of the body — a
            # hostile tenant omitting X-Kubernetes-Request-Items (or
            # under-declaring "1" for a large body) must not launder a
            # wide bulk into one seat. bulk_item_bytes sits at the
            # binary codec's minimal per-object footprint (~200 B/pod)
            # so honest binary declarations dominate the floor; verbose
            # encodings (JSON ~700 B/pod) pay bytes-proportional seats,
            # which tracks their parse cost. A normal single-object
            # create (a few KiB) stays at 1 seat.
            floor_items = max(1, content_length // self.bulk_item_bytes)
            return self._clamp(math.ceil(
                max(items_hint, floor_items) / self.items_per_seat))
        if items_hint > 0:
            return self._clamp(math.ceil(items_hint / self.items_per_seat))
        if is_collection_get:
            est = self._list_sizes.get(resource, 0.0)
            return self._clamp(math.ceil(est / self.list_objects_per_seat)
                               if est else 1)
        if content_length > self.bytes_per_seat:
            return self._clamp(1 + content_length // self.bytes_per_seat)
        return 1

    def _clamp(self, seats: int) -> int:
        return max(1, min(self.max_seats, seats))


def is_collection_path(path: str) -> bool:
    """A route addressing a whole collection (plural resource, no
    object name) — the shape both expensive lists and bulk mutations
    arrive on. One parser: ``faults.api_segments``."""
    return len(api_segments(path)) == 1


# ---------------------------------------------------------------------------
# the queueing machinery


# execution-time EWMA weight and the honest Retry-After drain estimate,
# shared by the APF levels and the legacy lanes: both 429 paths must
# advertise the SAME math for the same server state, so a tuning of the
# clamp window or the EWMA weight can never diverge them
_EXEC_EWMA = 0.8


def _ewma_exec(avg_s: float, sample_s: float) -> float:
    return _EXEC_EWMA * avg_s + (1.0 - _EXEC_EWMA) * sample_s


def _drain_hint_s(seats: float, avg_exec_s: float, capacity: int) -> float:
    """Expected time for ``seats`` of queued work to drain at
    ``capacity`` concurrency, clamped to a sane advertising window."""
    drain = seats * avg_exec_s / max(1, capacity)
    return round(min(13.0, max(0.05, drain)), 3)


_WAITING, _GRANTED, _ABANDONED = 0, 1, 2


class _QueuedRequest:
    __slots__ = ("event", "width", "flow_key", "state", "enqueued_at",
                 "queue")

    def __init__(self, width: int, flow_key: str):
        self.event = threading.Event()
        self.width = width
        self.flow_key = flow_key
        self.state = _WAITING
        self.enqueued_at = time.monotonic()
        self.queue: Optional["_Queue"] = None   # set at enqueue: the
        # timeout-dequeue path removes from THIS queue directly instead
        # of scanning every queue under the level lock at saturation


class _Queue:
    __slots__ = ("items", "seats_queued", "vwork")

    def __init__(self):
        self.items: collections.deque = collections.deque()
        self.seats_queued = 0
        self.vwork = 0.0        # cumulative dispatched seats (virtual work)


class Ticket:
    """Held while a request executes; ``release()`` (idempotent) frees
    the seats and dispatches queued work. Watches release EARLY — right
    after the stream attaches — so a long-lived connection charges only
    its initialization burst."""

    __slots__ = ("_level", "width", "schema", "_released", "_t0",
                 "exec_sample")

    def __init__(self, level: Optional["_PriorityLevel"], width: int,
                 schema: str):
        self._level = level
        self.width = width
        self.schema = schema
        self._released = False
        self._t0 = time.monotonic()
        # False for watch-init tickets: their early release (right
        # after stream attach, ~1ms) must NOT feed the level's
        # execution-time EWMA — under a reconnect herd those samples
        # would collapse avg_exec_s toward 0 and every 429's computed
        # Retry-After to its floor, amplifying the very retry storm the
        # honest hint exists to damp
        self.exec_sample = True

    @property
    def level_name(self) -> str:
        return self._level.name if self._level is not None else "exempt"

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        if self._level is not None:
            self._level.release(
                self.width,
                (time.monotonic() - self._t0) if self.exec_sample
                else None)


class _PriorityLevel:
    """One limited level: seat pool + shuffle-sharded fair QueueSet."""

    def __init__(self, spec: PriorityLevelSpec, capacity: int,
                 controller: "FlowController"):
        self.name = spec.name
        self.spec = spec
        self.capacity = max(2, int(capacity))
        self._controller = controller
        self._lock = threading.Lock()
        self._queues = [_Queue() for _ in range(max(1, spec.queues))]
        self._vbase = 0.0        # virtual clock floor for waking queues
        self.executing_seats = 0
        self.queued_seats = 0    # read lock-free by the shed check
        self.queued_requests = 0
        self.peak_executing = 0
        self.dispatched_total = 0
        self.seats_dispatched_total = 0
        self.rejected: Dict[str, int] = {}
        self.avg_exec_s = 0.05
        self.flows: Dict[str, int] = {}

    # -- admission -----------------------------------------------------
    def admit(self, flow_key: str, width: int, queue_wait_s: float,
              shed_active: bool, schema: str) -> Ticket:
        width = min(width, self.capacity)
        m = self._controller.metrics
        with self._lock:
            if self.queued_requests == 0 \
                    and self.executing_seats + width <= self.capacity:
                self._grant_locked(flow_key, width)
                return Ticket(self, width, schema)
            if shed_active and self.spec.sheddable:
                return self._reject_locked(schema, "shed", width)
            q = self._pick_queue_locked(flow_key)
            if len(q.items) >= self.spec.queue_length:
                return self._reject_locked(schema, "queue-full", width)
            req = _QueuedRequest(width, flow_key)
            req.queue = q
            if not q.items:
                q.vwork = max(q.vwork, self._vbase)
            q.items.append(req)
            q.seats_queued += width
            self.queued_seats += width
            self.queued_requests += 1
            # seats may be free even while requests queue (a wide
            # request ahead didn't fit): give fair dispatch a chance
            # NOW — without this, nothing runs until the next release
            # and a narrow request can 429 on timeout beside idle seats
            self._dispatch_locked()
            if m is not None:
                m.current_inqueue_requests.set(self.queued_requests,
                                               self.name)
        granted = req.event.wait(queue_wait_s)
        waited = time.monotonic() - req.enqueued_at
        if m is not None:
            m.request_queue_wait_seconds.observe(waited, self.name)
        if granted:
            return Ticket(self, width, schema)
        with self._lock:
            if req.state == _GRANTED:
                # the grant raced the timeout: seats are already charged
                return Ticket(self, width, schema)
            req.state = _ABANDONED
            # still queued (states only change under this lock): remove
            # the entry here so dispatch never sees abandoned requests
            return self._reject_locked(schema, "timeout", width,
                                       dequeue=req)

    def _reject_locked(self, schema: str, reason: str, width: int,
                       dequeue: Optional[_QueuedRequest] = None):
        if dequeue is not None:
            self.queued_seats -= width
            self.queued_requests -= 1
            dequeue.queue.items.remove(dequeue)
            dequeue.queue.seats_queued -= width
            # a timed-out WIDE head may have been the only thing keeping
            # narrower requests behind it from fitting: dispatch now, or
            # they too idle toward their own timeouts beside free seats
            self._dispatch_locked()
        self.rejected[reason] = self.rejected.get(reason, 0) + 1
        retry_after = self._retry_after_locked(width)
        m = self._controller.metrics
        if m is not None:
            m.rejected_requests_total.inc(self.name, reason)
            m.current_inqueue_requests.set(self.queued_requests, self.name)
        raise Rejected(self.name, schema, reason, retry_after)

    def _grant_locked(self, flow_key: str, width: int) -> None:
        self.executing_seats += width
        self.peak_executing = max(self.peak_executing, self.executing_seats)
        self.dispatched_total += 1
        self.seats_dispatched_total += width
        if len(self.flows) < 512 or flow_key in self.flows:
            self.flows[flow_key] = self.flows.get(flow_key, 0) + 1
        m = self._controller.metrics
        if m is not None:
            m.dispatched_requests_total.inc(self.name)
            m.seats_dispatched_total.inc(self.name, amount=width)
            m.current_executing_seats.set(self.executing_seats, self.name)
            if self.executing_seats > m.peak_executing_seats.get(self.name):
                m.peak_executing_seats.set(self.executing_seats, self.name)

    def _pick_queue_locked(self, flow_key: str) -> _Queue:
        hand = shuffle_shard_hand(
            _flow_hash(self.name, flow_key), len(self._queues),
            self.spec.hand_size)
        return min((self._queues[i] for i in hand),
                   key=lambda q: (len(q.items), q.seats_queued))

    # -- completion + fair dispatch ------------------------------------
    def release(self, width: int, duration: Optional[float]) -> None:
        """``duration=None`` frees the seats without sampling the
        execution-time EWMA (watch-init tickets — see Ticket)."""
        with self._lock:
            self.executing_seats -= width
            if duration is not None:
                self.avg_exec_s = _ewma_exec(self.avg_exec_s, duration)
            self._dispatch_locked()
            m = self._controller.metrics
            if m is not None:
                m.current_executing_seats.set(self.executing_seats,
                                              self.name)
                m.current_inqueue_requests.set(self.queued_requests,
                                               self.name)

    def _dispatch_locked(self) -> None:
        """Fair dispatch: repeatedly serve the non-empty queue with the
        least cumulative dispatched seats (virtual work) whose head
        fits the free seats — seat-weighted round-robin across flows,
        the queueset's min-virtual-finish-time discipline."""
        while True:
            best: Optional[_Queue] = None
            for q in self._queues:
                if q.items and (best is None or q.vwork < best.vwork):
                    best = q
            if best is None:
                return
            head = best.items[0]
            if self.executing_seats + head.width > self.capacity:
                return
            best.items.popleft()
            best.seats_queued -= head.width
            self.queued_seats -= head.width
            self.queued_requests -= 1
            self._vbase = max(self._vbase, best.vwork)
            best.vwork += head.width
            head.state = _GRANTED
            self._grant_locked(head.flow_key, head.width)
            head.event.set()

    # -- introspection -------------------------------------------------
    def _retry_after_locked(self, width: int) -> float:
        return _drain_hint_s(self.queued_seats + width, self.avg_exec_s,
                             self.capacity)

    def retry_after(self, width: int = 1) -> float:
        with self._lock:
            return self._retry_after_locked(width)

    def snapshot(self) -> Dict:
        m = self._controller.metrics
        qwait_p99 = m.request_queue_wait_seconds.quantile(
            0.99, self.name) if m is not None else 0.0
        with self._lock:
            return {
                "capacity": self.capacity,
                "sheddable": self.spec.sheddable,
                "queue_wait_p99_s": round(qwait_p99, 4),
                "executing_seats": self.executing_seats,
                "queued_requests": self.queued_requests,
                "queued_seats": self.queued_seats,
                "peak_executing_seats": self.peak_executing,
                "dispatched_total": self.dispatched_total,
                "seats_dispatched_total": self.seats_dispatched_total,
                "rejected": dict(self.rejected),
                "avg_exec_s": round(self.avg_exec_s, 4),
                "queue_depths": [len(q.items) for q in self._queues],
                "flows": dict(sorted(self.flows.items(),
                                     key=lambda kv: -kv[1])[:64]),
            }


class FlowController:
    """Classification + admission, one instance per APIServer. The
    uncontended hot path is: classify (a few precedence-ordered match
    calls), estimate width, one lock acquire to charge seats — the
    fairness machinery costs nothing until queues form."""

    def __init__(self, config: FlowControlConfig, metrics=None):
        self.config = config
        if metrics is None:
            from kubernetes_tpu.metrics.apf_metrics import apf_metrics

            metrics = apf_metrics()
        self.metrics = metrics
        self.width = WidthEstimator()
        limited = [lv for lv in config.levels if not lv.exempt]
        share_sum = sum(lv.shares for lv in limited) or 1
        self.levels: Dict[str, Optional[_PriorityLevel]] = {}
        self.total_capacity = 0
        for lv in config.levels:
            if lv.exempt:
                self.levels[lv.name] = None
                continue
            cap = max(2, round(config.total_seats * lv.shares / share_sum))
            level = _PriorityLevel(lv, cap, self)
            self.levels[lv.name] = level
            self.total_capacity += level.capacity
            if metrics is not None:
                metrics.request_concurrency_limit.set(level.capacity,
                                                      lv.name)
        self._schema_matched: Dict[str, int] = {}
        self._exempt_dispatched = 0
        # read-modify-write counters touched by every handler thread:
        # without this lock the /debug/apf match totals silently lose
        # increments under exactly the concurrency they diagnose
        self._stats_lock = threading.Lock()

    # -- classification ------------------------------------------------
    def classify(self, user: str, groups: Sequence[str], verb: str,
                 resource: str, namespace: str
                 ) -> Tuple[FlowSchema, Optional[_PriorityLevel]]:
        for schema in self.config.schemas:
            if schema.matches(user, groups, verb, resource, namespace):
                with self._stats_lock:
                    self._schema_matched[schema.name] = \
                        self._schema_matched.get(schema.name, 0) + 1
                return schema, self.levels[schema.priority_level]
        # unreachable with a catch-all schema; be safe anyway
        schema = self.config.schemas[-1]
        return schema, self.levels[schema.priority_level]

    def shed_active(self) -> bool:
        queued = sum(lv.queued_seats for lv in self.levels.values()
                     if lv is not None)
        return queued > self.config.shed_factor * self.total_capacity

    # -- admission -------------------------------------------------------
    def admit(self, user: str, groups: Sequence[str], verb: str,
              resource: str, namespace: str, flow_id: str = "",
              items_hint: int = 0, content_length: int = 0,
              is_watch: bool = False, path: str = "") -> Ticket:
        """Blocks while fairly queued; raises ``Rejected`` on queue-full
        / deadline / shed. Returns a ``Ticket`` to release on request
        completion (watches release right after attach)."""
        schema, level = self.classify(user, groups, verb, resource,
                                      namespace)
        if level is None:                      # exempt: never queued,
            with self._stats_lock:             # never charged seats
                self._exempt_dispatched += 1
            return Ticket(None, 0, schema.name)
        is_coll = bool(path) and is_collection_path(path)
        w = self.width.estimate(
            verb, resource,
            is_coll and verb in ("GET", "HEAD") and not is_watch,
            is_watch, items_hint, content_length,
            is_collection_mutation=is_coll
            and verb in ("POST", "PUT", "PATCH"))
        ticket = level.admit(schema.flow_key(user, namespace, flow_id), w,
                             self.config.queue_wait_s, self.shed_active(),
                             schema.name)
        if is_watch:
            # watch-init seats release at stream attach — milliseconds
            # that must not be mistaken for this level's execution time
            ticket.exec_sample = False
        return ticket

    # -- introspection ---------------------------------------------------
    def snapshot(self) -> Dict:
        return {
            "total_capacity": self.total_capacity,
            "queue_wait_s": self.config.queue_wait_s,
            "shed_factor": self.config.shed_factor,
            "shed_active": self.shed_active(),
            "exempt_dispatched_total": self._exempt_dispatched,
            "levels": {
                name: lv.snapshot()
                for name, lv in self.levels.items() if lv is not None
            },
            "schemas": [
                {"name": s.name, "precedence": s.precedence,
                 "priorityLevel": s.priority_level,
                 "matched_total": self._schema_matched.get(s.name, 0)}
                for s in self.config.schemas
            ],
        }


# ---------------------------------------------------------------------------
# legacy-lane Retry-After (the max-in-flight path keeps working when
# flow control is disabled, but its 429s must carry an honest hint too)


class LaneStats:
    """In-flight count + execution-time EWMA for one legacy lane, so a
    lane-full 429 can answer ``Retry-After = inflight x avg_exec /
    capacity`` (expected drain time) instead of a hard-coded 1s."""

    def __init__(self, capacity: Optional[int]):
        self.capacity = capacity or 1
        self.inflight = 0
        self.avg_exec_s = 0.05
        self._lock = threading.Lock()

    def start(self) -> None:
        with self._lock:
            self.inflight += 1

    def done(self, duration: float) -> None:
        with self._lock:
            self.inflight -= 1
            self.avg_exec_s = _ewma_exec(self.avg_exec_s, duration)

    def retry_after(self) -> float:
        with self._lock:
            return _drain_hint_s(max(1, self.inflight), self.avg_exec_s,
                                 self.capacity)
