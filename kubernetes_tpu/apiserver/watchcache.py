"""Revisioned watch cache over the cluster store's event feed.

Behavioral equivalent of the reference's apiserver watch cache + etcd3
watch semantics (``staging/src/k8s.io/apiserver/pkg/storage/cacher``,
``storage/etcd3/watcher.go``): every store mutation is appended to a
bounded in-memory event log keyed by the store's monotonically increasing
resource version, and a watch opened at resourceVersion=R first replays
every logged event with rv > R, then streams live — the List+Watch
contract client-go's Reflector depends on (``tools/cache/reflector.go:254``).

If R has already been compacted out of the log the watch fails with
``TooOldResourceVersion`` and the client must relist, exactly like etcd's
"required revision has been compacted" → reflector relist path.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, List, Optional, Tuple

from kubernetes_tpu.apiserver.store import ClusterStore, Event


class TooOldResourceVersion(Exception):
    """The requested resourceVersion predates the log window (etcd
    ErrCompacted → client must List again and watch from the new RV)."""


class CachedEvent:
    __slots__ = ("rv", "event")

    def __init__(self, rv: int, event: Event):
        self.rv = rv
        self.event = event


class WatchCache:
    """Bounded event log + live fan-out. One per cluster store."""

    def __init__(self, store: ClusterStore, capacity: int = 100_000):
        self._store = store
        self._capacity = capacity
        self._log: deque[CachedEvent] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._live: List[Callable[[int, Event], None]] = []
        # subscribe to the store; events carry the object's already-bumped
        # resourceVersion (DELETED events reuse the store's current rv)
        self._handle = store.watch(self._on_event)

    # -- ingestion -----------------------------------------------------
    def _rv_of(self, event: Event) -> int:
        rv = getattr(event.obj.metadata, "resource_version", "") or "0"
        try:
            return int(rv)
        except ValueError:
            return 0

    def _on_event(self, event: Event) -> None:
        rv = self._rv_of(event)
        with self._lock:
            self._log.append(CachedEvent(rv, event))
            sinks = list(self._live)
        for fn in sinks:
            fn(rv, event)

    # -- watch API -----------------------------------------------------
    def oldest_rv(self) -> Optional[int]:
        with self._lock:
            return self._log[0].rv if self._log else None

    def latest_rv(self) -> int:
        with self._lock:
            return self._log[-1].rv if self._log else 0

    def watch_from(
        self, resource_version: int, fn: Callable[[int, Event], None]
    ) -> "WatchCacheHandle":
        """Replay logged events with rv > resource_version, then attach
        live. Replay and attach happen under one lock acquisition so no
        event is missed or duplicated at the seam."""
        with self._lock:
            if self._log:
                oldest = self._log[0].rv
                # a client at rv < oldest-1 may have missed compacted events
                if resource_version < oldest - 1:
                    raise TooOldResourceVersion(
                        f"resourceVersion {resource_version} is too old "
                        f"(oldest logged: {oldest})"
                    )
                replay = [ce for ce in self._log if ce.rv > resource_version]
            else:
                replay = []
            # dispatch replay before any new live event can interleave
            for ce in replay:
                fn(ce.rv, ce.event)
            self._live.append(fn)
            return WatchCacheHandle(self, fn)

    def _remove(self, fn) -> None:
        with self._lock:
            if fn in self._live:
                self._live.remove(fn)

    def compact(self, keep_last: int) -> None:
        """Drop all but the newest keep_last events (etcd compaction)."""
        with self._lock:
            while len(self._log) > keep_last:
                self._log.popleft()

    def stop(self) -> None:
        self._handle.stop()


class WatchCacheHandle:
    def __init__(self, cache: WatchCache, fn):
        self._cache = cache
        self._fn = fn

    def stop(self) -> None:
        self._cache._remove(self._fn)
