from kubernetes_tpu.apiserver.store import ClusterStore, Event, WatchHandle
