"""Binary wire codec for control-plane components.

The reference apiserver negotiates a binary serialization alongside JSON
(``staging/src/k8s.io/apimachinery/pkg/runtime/serializer/protobuf/
protobuf.go``: ``application/vnd.kubernetes.protobuf``) because JSON
encode/decode dominates wire cost at scheduler_perf scale. This module
is the analog: API objects travel as pickled Python objects (protocol
5), negotiated per request via ``Content-Type`` / ``Accept``.

Measured on this codebase (256-pod batch): pickle ~9 µs/pod each way vs
~80 µs ``to_wire``+``json.dumps`` and ~110 µs ``json.loads``+
``from_wire`` — the same order of win protobuf buys the reference.

Trust model: pickle is only safe between same-codebase control-plane
components (exactly protobuf's deployment envelope in the reference —
kubelet/scheduler/controller-manager speak it, kubectl speaks JSON).
The server therefore only decodes binary BODIES from authenticated
clients (or when it was built with no authentication at all, the
in-process test topology); anonymous remote callers cannot reach the
unpickler. Responses are only pickled when the client explicitly asks
via ``Accept``.
"""

from __future__ import annotations

import pickle
from typing import Any

# the negotiated media type (reference: application/vnd.kubernetes.protobuf)
BINARY_CONTENT_TYPE = "application/vnd.ktpu.binary"

# -- wire-version negotiation (mixed-version skew guard) ---------------
# A rolling upgrade has old and new processes on the wire at once
# (upstream's N/N-1 skew contract). The codec's one observable schema
# change so far is the watch-event frame: v1 streamed ``(type, obj,
# old)`` 3-tuples, v2 streams ``(type, obj, old, commit_ts)`` 4-tuples.
# Decoders were written to accept both, but that is an accident of this
# particular change — the next one may not be shape-sniffable. So the
# contract is made EXPLICIT: the client stamps the highest version it
# speaks on every request (VERSION_HEADER), the server pins the
# connection to ``min(server, client)`` and echoes the pinned stamp
# back; an out-of-range stamp is a 400, never a silent decode skew.
# Absent header → v2 (every current in-tree client already speaks it;
# the stamp exists for the NEXT skew, and for v1-pinned laggards).
CODEC_VERSION = 2
MIN_CODEC_VERSION = 1
VERSION_HEADER = "X-Ktpu-Codec-Version"


def negotiate(client_stamp) -> int:
    """Pin the wire version for one request: ``min(server, client)``.

    ``client_stamp`` is the raw header value (or None when absent).
    Raises ValueError when the stamp is malformed or outside
    [MIN_CODEC_VERSION, ∞) — a client OLDER than the server's floor
    cannot be served and must be told so explicitly (the server no
    longer encodes that schema), and garbage must not default-through
    to a guess."""
    if client_stamp is None:
        return CODEC_VERSION
    v = int(client_stamp)  # ValueError on garbage propagates
    if v < MIN_CODEC_VERSION:
        raise ValueError(
            f"codec version {v} below server floor {MIN_CODEC_VERSION}")
    return min(CODEC_VERSION, v)

# watch streams prefix each frame with a 4-byte big-endian length (the
# reference streams length-delimited protobuf frames the same way:
# runtime/serializer/streaming). A frame's payload is a pickled LIST
# whose elements are per-event pickles (bytes) — encoded once
# server-side and cached on the event (rest.py _cached_event_bytes), so
# coalescing a chunk is a list-of-bytes pickle (memcpy per element),
# never a re-encode. A frame cut mid-event reads as torn (read_frame →
# None): the client relists, exactly like a torn JSON line.
FRAME_LEN_BYTES = 4


def encode(payload: Any) -> bytes:
    return pickle.dumps(payload, protocol=5)


def decode(data: bytes) -> Any:
    return pickle.loads(data)


def frame(payload: Any) -> bytes:
    body = encode(payload)
    return len(body).to_bytes(FRAME_LEN_BYTES, "big") + body


def read_frame(fp) -> Any:
    """Read one length-prefixed frame from a file-like; None on EOF."""
    header = fp.read(FRAME_LEN_BYTES)
    if not header or len(header) < FRAME_LEN_BYTES:
        return None
    n = int.from_bytes(header, "big")
    body = fp.read(n)
    if len(body) < n:
        return None
    return decode(body)
