"""Binary wire codec for control-plane components.

The reference apiserver negotiates a binary serialization alongside JSON
(``staging/src/k8s.io/apimachinery/pkg/runtime/serializer/protobuf/
protobuf.go``: ``application/vnd.kubernetes.protobuf``) because JSON
encode/decode dominates wire cost at scheduler_perf scale. This module
is the analog: API objects travel as pickled Python objects (protocol
5), negotiated per request via ``Content-Type`` / ``Accept``.

Measured on this codebase (256-pod batch): pickle ~9 µs/pod each way vs
~80 µs ``to_wire``+``json.dumps`` and ~110 µs ``json.loads``+
``from_wire`` — the same order of win protobuf buys the reference.

Trust model: pickle is only safe between same-codebase control-plane
components (exactly protobuf's deployment envelope in the reference —
kubelet/scheduler/controller-manager speak it, kubectl speaks JSON).
The server therefore only decodes binary BODIES from authenticated
clients (or when it was built with no authentication at all, the
in-process test topology); anonymous remote callers cannot reach the
unpickler. Responses are only pickled when the client explicitly asks
via ``Accept``.
"""

from __future__ import annotations

import pickle
from typing import Any

# the negotiated media type (reference: application/vnd.kubernetes.protobuf)
BINARY_CONTENT_TYPE = "application/vnd.ktpu.binary"

# watch streams prefix each frame with a 4-byte big-endian length (the
# reference streams length-delimited protobuf frames the same way:
# runtime/serializer/streaming). A frame's payload is a pickled LIST
# whose elements are per-event pickles (bytes) — encoded once
# server-side and cached on the event (rest.py _cached_event_bytes), so
# coalescing a chunk is a list-of-bytes pickle (memcpy per element),
# never a re-encode. A frame cut mid-event reads as torn (read_frame →
# None): the client relists, exactly like a torn JSON line.
FRAME_LEN_BYTES = 4


def encode(payload: Any) -> bytes:
    return pickle.dumps(payload, protocol=5)


def decode(data: bytes) -> Any:
    return pickle.loads(data)


def frame(payload: Any) -> bytes:
    body = encode(payload)
    return len(body).to_bytes(FRAME_LEN_BYTES, "big") + body


def read_frame(fp) -> Any:
    """Read one length-prefixed frame from a file-like; None on EOF."""
    header = fp.read(FRAME_LEN_BYTES)
    if not header or len(header) < FRAME_LEN_BYTES:
        return None
    n = int.from_bytes(header, "big")
    body = fp.read(n)
    if len(body) < n:
        return None
    return decode(body)
