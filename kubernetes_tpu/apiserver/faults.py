"""Wire-level fault injection for the REST fabric (the chaos-over-REST
half of the chaos ring; reference ``test/e2e/chaosmonkey`` + the
apiserver's own failure modes clients must survive: connection resets,
truncated responses, added latency, 429/503 overload pushback, stalled
and dropped watch streams).

A ``FaultGate`` sits in front of the handler chain in ``rest.py``. Rules
match per-verb and per-resource, fire with a configured probability from
a SEEDED RNG (a chaos run replays exactly), and optionally carry a
finite ``count`` (bursts). The gate is togglable at runtime through the
``/debug/faults`` admin endpoint, which is itself never faulted — chaos
must not be able to lock you out of the chaos controls.

Fault vocabulary:

- ``reset``        — abort the TCP connection (SO_LINGER 0 → RST), no
                     response bytes at all;
- ``truncate``     — serve the real response but cut the byte stream
                     after ``truncate_bytes``, then abort;
- ``latency``      — sleep ``latency`` seconds, then serve normally;
- ``error``        — answer ``code`` (429/503) with ``Retry-After``;
- ``watch_stall``  — pause a watch stream ``duration`` seconds before
                     the next frame;
- ``watch_drop``   — abort a watch stream mid-flight (no terminating
                     chunk), forcing the client's relist path.

Every injection increments ``faults_injected_total{fault,resource}``.
"""

from __future__ import annotations

import random
import threading
from typing import Dict, List, Optional

FAULTS = ("reset", "truncate", "latency", "error",
          "watch_stall", "watch_drop")
_WATCH_FAULTS = ("watch_stall", "watch_drop")


def api_segments(path: str) -> List[str]:
    """Resource-route segments of an API path with the ``/api/v1`` or
    ``/apis/<group>/<version>`` prefix and any ``namespaces/<ns>`` pair
    stripped (kept when the namespace itself IS the object, as in
    ``/api/v1/namespaces/default``). The ONE route parser behind fault
    matching and flowcontrol's width estimation — a future route-shape
    change lands here, not in per-module copies."""
    parts = [p for p in path.split("?", 1)[0].split("/") if p]
    if not parts:
        return []
    if parts[0] == "api":
        rest = parts[2:]        # /api/v1/...
    elif parts[0] == "apis":
        rest = parts[3:]        # /apis/<g>/<v>/...
    else:
        return []
    if rest and rest[0] == "namespaces" and len(rest) >= 3:
        rest = rest[2:]
    return rest


def resource_of(path: str) -> str:
    """Plural resource segment of an API path ("pods", "nodes", ...);
    "" for non-resource paths. Mirrors the route logic in rest.py
    without needing the resolved kind."""
    rest = api_segments(path)
    return rest[0] if rest else ""


def namespace_of(path: str) -> str:
    """Namespace segment of an API path; "" when cluster-scoped."""
    parts = [p for p in path.split("?", 1)[0].split("/") if p]
    for i, part in enumerate(parts):
        if part == "namespaces" and i + 1 < len(parts):
            return parts[i + 1]
    return ""


class FaultRule:
    """One matching rule. ``count=None`` means unlimited; a finite count
    is decremented per injection (the "burst" shape: N consecutive 429s,
    one reset, ...)."""

    def __init__(self, fault: str, verb: str = "*", resource: str = "*",
                 probability: float = 1.0, count: Optional[int] = None,
                 latency: float = 0.05, code: int = 503,
                 retry_after: float = 1.0, truncate_bytes: int = 120,
                 duration: float = 0.5):
        if fault not in FAULTS:
            raise ValueError(f"unknown fault {fault!r} (one of {FAULTS})")
        if not 0.0 <= probability <= 1.0:
            raise ValueError(f"probability out of range: {probability}")
        if fault == "error" and code not in (429, 500, 503):
            raise ValueError(f"error fault code must be 429/500/503, "
                             f"got {code}")
        self.fault = fault
        self.verb = verb.upper()
        self.resource = resource
        self.probability = float(probability)
        self.count = None if count is None else int(count)
        self.latency = float(latency)
        self.code = int(code)
        self.retry_after = float(retry_after)
        self.truncate_bytes = int(truncate_bytes)
        self.duration = float(duration)

    def matches(self, verb: str, resource: str, watch: bool) -> bool:
        if watch != (self.fault in _WATCH_FAULTS):
            return False
        if self.verb != "*" and self.verb != verb.upper():
            return False
        if self.resource != "*" and self.resource != resource:
            return False
        return True

    @classmethod
    def from_dict(cls, spec: Dict) -> "FaultRule":
        known = {"fault", "verb", "resource", "probability", "count",
                 "latency", "code", "retry_after", "truncate_bytes",
                 "duration"}
        unknown = set(spec) - known
        if unknown:
            raise ValueError(f"unknown rule fields: {sorted(unknown)}")
        return cls(**spec)

    def to_dict(self) -> Dict:
        return {
            "fault": self.fault, "verb": self.verb,
            "resource": self.resource, "probability": self.probability,
            "count": self.count, "latency": self.latency,
            "code": self.code, "retry_after": self.retry_after,
            "truncate_bytes": self.truncate_bytes,
            "duration": self.duration,
        }


class FaultGate:
    """Seeded, runtime-reconfigurable fault decider. With no rules the
    per-request cost is one attribute read — the gate always exists, so
    steady-state benchmarks pay nothing measurable."""

    def __init__(self, seed: int = 0, metrics=None):
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        self._seed = seed
        self._rules: List[FaultRule] = []
        self._injected: Dict[tuple, int] = {}
        self._metrics = metrics

    # -- configuration (admin endpoint) --------------------------------
    def configure(self, spec: Dict) -> None:
        """Replace the rule set atomically. ``{"seed": S, "rules":
        [...]}`` — a new seed restarts the RNG so a matrix run is
        reproducible per (seed, rule set)."""
        rules = [FaultRule.from_dict(r) for r in spec.get("rules") or ()]
        with self._lock:
            if "seed" in spec:
                self._seed = int(spec["seed"])
                self._rng = random.Random(self._seed)
            self._rules = rules

    def add_rule(self, rule: FaultRule) -> None:
        with self._lock:
            self._rules = self._rules + [rule]

    def clear(self) -> None:
        with self._lock:
            self._rules = []

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "seed": self._seed,
                "rules": [r.to_dict() for r in self._rules],
                "injected": {
                    f"{fault}/{resource}": n
                    for (fault, resource), n in sorted(self._injected.items())
                },
            }

    # -- the hot path --------------------------------------------------
    def decide(self, verb: str, resource: str,
               watch: bool = False) -> Optional[FaultRule]:
        """First matching rule that fires, or None. Decisions consume
        the shared RNG under the lock, so a single-threaded request
        sequence replays exactly per seed."""
        if not self._rules:          # steady state: one attribute read
            return None
        with self._lock:
            for rule in self._rules:
                if not rule.matches(verb, resource, watch):
                    continue
                if rule.count is not None and rule.count <= 0:
                    continue
                if rule.probability < 1.0 and \
                        self._rng.random() >= rule.probability:
                    continue
                if rule.count is not None:
                    rule.count -= 1
                key = (rule.fault, resource or "-")
                self._injected[key] = self._injected.get(key, 0) + 1
                metrics = self._metrics
                break
            else:
                return None
        if metrics is None:
            from kubernetes_tpu.metrics.fabric_metrics import fabric_metrics

            metrics = self._metrics = fabric_metrics()
        metrics.faults_injected_total.inc(rule.fault, resource or "-")
        return rule

    def injected_total(self) -> int:
        with self._lock:
            return sum(self._injected.values())
