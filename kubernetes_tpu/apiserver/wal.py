"""Durable storage behind the ClusterStore: append-only WAL + snapshots.

The reference persists every API object through ``storage.Interface`` to
etcd (``staging/.../storage/etcd3/store.go:86``) — etcd itself being a
WAL + snapshot state machine. This module closes the same architectural
gap for the in-process store: every watch-visible mutation (the store
dispatches one event per mutation, in commit order, under the store
lock) is appended to a JSON-lines log; a snapshot of the full object
space is cut when the log grows past ``snapshot_every`` entries; and
``restore_store`` rebuilds a ClusterStore from snapshot + log replay —
preserving object identity, resource versions, and the revision counter,
so watches resumed against the restored store keep etcd-style semantics.

Usage::

    store = ClusterStore()
    wal = attach_wal(store, "/var/lib/ktpu")     # from then on: durable
    ...
    # after a crash:
    store2 = restore_store("/var/lib/ktpu")

Durability level: writes are buffered and flushed per append;
``fsync=True`` additionally fsyncs each append (etcd's default), at a
large throughput cost — the right setting for a real deployment, the
wrong one for a benchmark harness.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Optional

from kubernetes_tpu.api.serialization import from_wire, to_wire
from kubernetes_tpu.apiserver.store import DELETED, ClusterStore, Event

LOG_NAME = "wal.jsonl"
SNAP_NAME = "snapshot.json"
SNAP_TMP = "snapshot.json.tmp"


class WalHandle:
    def __init__(self, store: ClusterStore, directory: str,
                 snapshot_every: int = 20000, fsync: bool = False):
        self.store = store
        self.dir = directory
        self.snapshot_every = snapshot_every
        self.fsync = fsync
        os.makedirs(directory, exist_ok=True)
        self._log_path = os.path.join(directory, LOG_NAME)
        self._log = open(self._log_path, "a", encoding="utf-8")
        self._entries_since_snapshot = 0
        # the store dispatches synchronously under ITS lock; this lock
        # only guards against snapshot() racing an append from a
        # different store (not a supported topology, but cheap)
        self._lock = threading.Lock()
        self._watch = store.watch(self._on_event)

    # ------------------------------------------------------------------
    def _on_event(self, event: Event) -> None:
        obj = event.obj
        rv = getattr(obj.metadata, "resource_version", "") or "0"
        if event.type == DELETED:
            line = {
                "t": "DEL", "k": event.kind, "rv": int(rv),
                "ns": getattr(obj.metadata, "namespace", ""),
                "n": obj.metadata.name,
            }
        else:
            line = {"t": "PUT", "k": event.kind, "rv": int(rv),
                    "o": to_wire(obj)}
        with self._lock:
            self._log.write(json.dumps(line) + "\n")
            self._log.flush()
            if self.fsync:
                os.fsync(self._log.fileno())
            self._entries_since_snapshot += 1
            if self._entries_since_snapshot >= self.snapshot_every:
                self._snapshot_locked()

    # ------------------------------------------------------------------
    def snapshot(self) -> None:
        """Cut a snapshot now and truncate the log (etcd compaction).
        Lock order is store -> wal, matching _on_event (which runs under
        the store lock via the synchronous dispatch) — the store lock is
        reentrant, so taking it first here and again inside
        _snapshot_locked is safe, and AB/BA inversion is impossible."""
        with self.store._lock:
            with self._lock:
                self._snapshot_locked()

    def _snapshot_locked(self) -> None:
        objects = []
        with self.store._lock:   # reentrant: callers already hold it
            rv = self.store._rv
            # known_kinds lists CustomResourceDefinition (a built-in)
            # before the custom kinds it defines, so restore re-registers
            # each kind before replaying its instances
            for kind in self.store.known_kinds():
                table, _ = self.store._kind_entry(kind)
                for obj in table.values():
                    objects.append([kind, to_wire(obj)])
        tmp = os.path.join(self.dir, SNAP_TMP)
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"rv": rv, "objects": objects}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.dir, SNAP_NAME))
        self._log.close()
        self._log = open(self._log_path, "w", encoding="utf-8")
        self._entries_since_snapshot = 0

    def close(self) -> None:
        self._watch.stop()
        with self._lock:
            self._log.close()


def attach_wal(store: ClusterStore, directory: str,
               snapshot_every: int = 20000, fsync: bool = False) -> WalHandle:
    """Make ``store`` durable: all subsequent mutations are logged.
    Cuts an initial snapshot so pre-existing state is captured too."""
    handle = WalHandle(store, directory, snapshot_every=snapshot_every,
                       fsync=fsync)
    handle.snapshot()
    return handle


def restore_store(directory: str,
                  store: Optional[ClusterStore] = None) -> ClusterStore:
    """Rebuild a ClusterStore from snapshot + WAL replay (crash
    recovery: the store process restarts; clients re-list-and-watch,
    reference resume semantics — SURVEY.md section 5 checkpoint/resume).
    Resource versions and the revision counter survive, so a resumed
    watch sees a monotonic history."""
    store = store if store is not None else ClusterStore()
    max_rv = 0
    snap_path = os.path.join(directory, SNAP_NAME)
    if os.path.exists(snap_path):
        with open(snap_path, encoding="utf-8") as f:
            snap = json.load(f)
        max_rv = int(snap.get("rv") or 0)
        with store._lock:
            for kind, wire in snap.get("objects", ()):
                obj = from_wire(wire, kind)
                if kind == "CustomResourceDefinition":
                    store._register_crd_locked(obj)
                table, key = store._table_key(
                    kind, obj.metadata.namespace, obj.metadata.name
                )
                table[key] = obj
    log_path = os.path.join(directory, LOG_NAME)
    if os.path.exists(log_path):
        with open(log_path, encoding="utf-8") as f:
            for raw in f:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    line = json.loads(raw)
                except json.JSONDecodeError:
                    break  # torn tail write from the crash: stop replay
                max_rv = max(max_rv, int(line.get("rv") or 0))
                kind = line["k"]
                if line["t"] == "DEL":
                    try:
                        table, key = store._table_key(
                            kind, line.get("ns", ""), line["n"]
                        )
                    except KeyError:
                        continue  # delete of an already-unregistered kind
                    old = table.pop(key, None)
                    if kind == "CustomResourceDefinition" and \
                            old is not None:
                        store._unregister_crd_locked(old)
                else:
                    obj = from_wire(line["o"], kind)
                    if kind == "CustomResourceDefinition":
                        store._register_crd_locked(obj)
                    table, key = store._table_key(
                        kind, obj.metadata.namespace, obj.metadata.name
                    )
                    table[key] = obj
    with store._lock:
        store._rv = max(store._rv, max_rv)
    return store
