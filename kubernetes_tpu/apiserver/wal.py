"""Durable storage behind the ClusterStore: append-only WAL + snapshots.

The reference persists every API object through ``storage.Interface`` to
etcd (``staging/.../storage/etcd3/store.go:86``) — etcd itself being a
WAL + snapshot state machine. This module closes the same architectural
gap for the in-process store: every watch-visible mutation (the store
dispatches one event per mutation, in commit order, under the store
lock) is appended to a JSON-lines log; a snapshot of the full object
space is cut when the log grows past ``snapshot_every`` entries; and
``restore_store`` rebuilds a ClusterStore from snapshot + log replay —
preserving object identity, resource versions, and the revision counter,
so watches resumed against the restored store keep etcd-style semantics.

Usage::

    store = ClusterStore()
    wal = attach_wal(store, "/var/lib/ktpu")     # from then on: durable
    ...
    # after a crash:
    store2 = restore_store("/var/lib/ktpu")

Durability level: writes are buffered and flushed per append;
``fsync=True`` additionally fsyncs each append (etcd's default), at a
large throughput cost — the right setting for a real deployment, the
wrong one for a benchmark harness.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Optional

from kubernetes_tpu.api.serialization import from_wire, to_wire
from kubernetes_tpu.apiserver.store import DELETED, ClusterStore, Event

LOG_NAME = "wal.jsonl"
SNAP_NAME = "snapshot.json"
SNAP_TMP = "snapshot.json.tmp"


class WalHandle:
    """``async_serialize=True`` (the default) moves serialization off
    the store lock: the watch callback only enqueues the event (the
    store hands watchers freshly-built objects that later mutations
    never touch, so holding a reference is snapshot-safe) and a writer
    thread serializes + appends in commit order. This is etcd's own
    shape — raft appends are pipelined behind the apply loop, not paid
    inside each request's critical section. ``fsync=True`` forces the
    synchronous inline path (every mutation durable before its watch
    event is visible)."""

    def __init__(self, store: ClusterStore, directory: str,
                 snapshot_every: int = 20000, fsync: bool = False,
                 async_serialize: Optional[bool] = None):
        self.store = store
        self.dir = directory
        self.snapshot_every = snapshot_every
        self.fsync = fsync
        # conservative default: serialize inline (every mutation on disk
        # before its watch event returns) — the chaos ring's WAL-equality
        # invariant depends on it. High-throughput servers opt into the
        # async writer and accept a queue-bounded loss window on crash.
        self.async_serialize = bool(async_serialize)
        os.makedirs(directory, exist_ok=True)
        self._log_path = os.path.join(directory, LOG_NAME)
        self._log = open(self._log_path, "a", encoding="utf-8")
        self._entries_since_snapshot = 0
        # the store dispatches synchronously under ITS lock; this lock
        # only guards against snapshot() racing an append from a
        # different store (not a supported topology, but cheap)
        self._lock = threading.Lock()
        self._queue: "queue.Queue[Optional[Event]]" = queue.Queue()
        self._writer: Optional[threading.Thread] = None
        if self.async_serialize:
            self._writer = threading.Thread(
                target=self._writer_loop, daemon=True, name="wal-writer")
            self._writer.start()
        self._watch = store.watch(self._on_event,
                                  batch_fn=self._on_events)
        # the silent placement channel (adopt/evict during live
        # partition resharding): watcher-invisible by design, but it
        # MUST reach the log — a failover restore that misses an
        # adopted slice loses it, one that misses an eviction
        # resurrects it on the wrong partition
        self._silent_watch = store.watch_silent(self._on_events) \
            if hasattr(store, "watch_silent") else None

    # ------------------------------------------------------------------
    def _on_events(self, events) -> None:
        if self.async_serialize:
            for event in events:
                self._queue.put(event)
        else:
            for event in events:
                self._append(event)

    def _on_event(self, event: Event) -> None:
        self._on_events([event])

    def _writer_loop(self) -> None:
        while True:
            event = self._queue.get()
            if event is None:
                return
            try:
                self._append(event)
            except Exception:   # noqa: BLE001 — a bad record must not
                pass            # kill durability for all that follow
            if self._entries_since_snapshot >= self.snapshot_every:
                # compaction between queue items, store→wal lock order
                # (never from inside _append, whose wal→store order
                # would invert against snapshot())
                try:
                    self.snapshot()
                except Exception:   # noqa: BLE001
                    pass

    def _line_for(self, event: Event) -> str:
        obj = event.obj
        rv = getattr(obj.metadata, "resource_version", "") or "0"
        if event.type == DELETED:
            line = {
                "t": "DEL", "k": event.kind, "rv": int(rv),
                "ns": getattr(obj.metadata, "namespace", ""),
                "n": obj.metadata.name,
            }
        else:
            line = {"t": "PUT", "k": event.kind, "rv": int(rv),
                    "o": to_wire(obj)}
        return json.dumps(line)

    def _append(self, event: Event) -> None:
        line = self._line_for(event)
        with self._lock:
            self._log.write(line + "\n")
            self._log.flush()
            if self.fsync:
                os.fsync(self._log.fileno())
            self._entries_since_snapshot += 1
            if not self.async_serialize and \
                    self._entries_since_snapshot >= self.snapshot_every:
                # sync path runs under the (reentrant) store lock via
                # the dispatch, so store→wal order holds here
                self._snapshot_locked()

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until every enqueued event is on disk."""
        deadline = time.monotonic() + timeout
        while not self._queue.empty():
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.01)
        return True

    # ------------------------------------------------------------------
    def snapshot(self) -> None:
        """Cut a snapshot now and truncate the log (etcd compaction).
        Lock order is store -> wal everywhere (the sync dispatch path
        holds the reentrant store lock already; the async writer calls
        this between queue items, holding neither). With the store lock
        held no new events can enqueue, and draining first keeps the
        truncated log free of entries the snapshot already contains —
        restore's per-object rv guard covers the writer's own calls,
        which skip the drain (the writer cannot wait on itself)."""
        with self.store._lock:
            if self._writer is not None and \
                    threading.current_thread() is not self._writer:
                self.drain()
            with self._lock:
                self._snapshot_locked()

    def _snapshot_locked(self) -> None:
        objects = []
        with self.store._lock:   # reentrant: callers already hold it
            rv = self.store._rv
            # known_kinds lists CustomResourceDefinition (a built-in)
            # before the custom kinds it defines, so restore re-registers
            # each kind before replaying its instances
            for kind in self.store.known_kinds():
                table, _ = self.store._kind_entry(kind)
                for obj in table.values():
                    objects.append([kind, to_wire(obj)])
        tmp = os.path.join(self.dir, SNAP_TMP)
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"rv": rv, "objects": objects}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.dir, SNAP_NAME))
        self._log.close()
        self._log = open(self._log_path, "w", encoding="utf-8")
        self._entries_since_snapshot = 0

    def close(self) -> None:
        self._watch.stop()
        if self._silent_watch is not None:
            self._silent_watch.stop()
        if self._writer is not None:
            self.drain()
            self._queue.put(None)
            self._writer.join(timeout=5.0)
        with self._lock:
            self._log.close()


def attach_wal(store: ClusterStore, directory: str,
               snapshot_every: int = 20000, fsync: bool = False,
               async_serialize: bool = False,
               preserve_log: bool = False) -> WalHandle:
    """Make ``store`` durable: all subsequent mutations are logged.
    Cuts an initial snapshot so pre-existing state is captured too.

    ``preserve_log=True`` (restart-after-restore): skip the initial
    snapshot — which would TRUNCATE the log — and append to the
    existing one instead (after repairing a torn tail from the crash).
    The read tier depends on this: a replica resuming its subscription
    across an owner restart replays the missed window from this log
    (``wal_events_since``); a truncating attach would swallow exactly
    the events between the replica's cursor and the crash and force a
    full reseed."""
    if preserve_log:
        _repair_log_tail(os.path.join(directory, LOG_NAME))
    handle = WalHandle(store, directory, snapshot_every=snapshot_every,
                       fsync=fsync, async_serialize=async_serialize)
    if not preserve_log:
        handle.snapshot()
    return handle


def _repair_log_tail(path: str) -> None:
    """Truncate a torn (crash-interrupted) final line so appends start
    on a clean line boundary. Restore already tolerates the torn tail
    by stopping replay; appending AFTER it would glue the next record
    onto the fragment and lose it too."""
    if not os.path.exists(path):
        return
    with open(path, "rb") as f:
        data = f.read()
    if not data or data.endswith(b"\n"):
        return
    cut = data.rfind(b"\n")
    os.truncate(path, cut + 1 if cut >= 0 else 0)


def wal_events_since(directory: str, cursor: int):
    """Parsed WAL entries with rv > ``cursor`` — the subscription
    endpoint's resume source when its in-memory watch cache cannot
    cover the window (a restarted owner starts with an empty cache).
    Returns ``(covered, entries)``: ``covered`` is False when
    compaction may have swallowed part of the window (a snapshot newer
    than the cursor with no log line at-or-below it) — the caller must
    answer 410 and the replica reseeds. Entries keep the on-disk shape
    ({"t": "PUT"/"DEL", "k": kind, "rv": rv, ...}); duplicates below
    the replica's per-object guard are harmless by contract."""
    snap_rv = 0
    snap_path = os.path.join(directory, SNAP_NAME)
    if os.path.exists(snap_path):
        try:
            with open(snap_path, encoding="utf-8") as f:
                snap_rv = int((json.load(f) or {}).get("rv") or 0)
        except (json.JSONDecodeError, OSError, ValueError):
            return False, []
    entries = []
    min_rv = None
    log_path = os.path.join(directory, LOG_NAME)
    if os.path.exists(log_path):
        with open(log_path, encoding="utf-8") as f:
            for raw in f:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    line = json.loads(raw)
                except json.JSONDecodeError:
                    break   # torn tail write from a crash: stop here
                line_rv = int(line.get("rv") or 0)
                if min_rv is None or line_rv < min_rv:
                    min_rv = line_rv
                if line_rv > cursor:
                    entries.append(line)
    covered = cursor >= snap_rv \
        or (min_rv is not None and min_rv <= cursor + 1)
    return covered, entries


def restore_store(directory: str,
                  store: Optional[ClusterStore] = None) -> ClusterStore:
    """Rebuild a ClusterStore from snapshot + WAL replay (crash
    recovery: the store process restarts; clients re-list-and-watch,
    reference resume semantics — SURVEY.md section 5 checkpoint/resume).
    Resource versions and the revision counter survive, so a resumed
    watch sees a monotonic history."""
    store = store if store is not None else ClusterStore()
    max_rv = 0
    snap_path = os.path.join(directory, SNAP_NAME)
    if os.path.exists(snap_path):
        with open(snap_path, encoding="utf-8") as f:
            snap = json.load(f)
        max_rv = int(snap.get("rv") or 0)
        with store._lock:
            for kind, wire in snap.get("objects", ()):
                obj = from_wire(wire, kind)
                if kind == "CustomResourceDefinition":
                    store._register_crd_locked(obj)
                table, key = store._table_key(
                    kind, obj.metadata.namespace, obj.metadata.name
                )
                table[key] = obj
    log_path = os.path.join(directory, LOG_NAME)
    if os.path.exists(log_path):
        with open(log_path, encoding="utf-8") as f:
            for raw in f:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    line = json.loads(raw)
                except json.JSONDecodeError:
                    break  # torn tail write from the crash: stop replay
                line_rv = int(line.get("rv") or 0)
                max_rv = max(max_rv, line_rv)
                kind = line["k"]

                def newer_exists(table, key) -> bool:
                    # per-object rv guard: the async writer may append
                    # (after a compaction it didn't wait for) entries
                    # the snapshot already contains — replaying them
                    # must never regress a newer object
                    cur = table.get(key)
                    if cur is None:
                        return False
                    cur_rv = int(getattr(cur.metadata, "resource_version",
                                         "") or 0)
                    return cur_rv > line_rv
                if line["t"] == "DEL":
                    try:
                        table, key = store._table_key(
                            kind, line.get("ns", ""), line["n"]
                        )
                    except KeyError:
                        continue  # delete of an already-unregistered kind
                    if newer_exists(table, key):
                        continue
                    old = table.pop(key, None)
                    if kind == "CustomResourceDefinition" and \
                            old is not None:
                        store._unregister_crd_locked(old)
                else:
                    obj = from_wire(line["o"], kind)
                    if kind == "CustomResourceDefinition":
                        store._register_crd_locked(obj)
                    table, key = store._table_key(
                        kind, obj.metadata.namespace, obj.metadata.name
                    )
                    if newer_exists(table, key):
                        continue
                    table[key] = obj
    with store._lock:
        store._rv = max(store._rv, max_rv)
    return store
