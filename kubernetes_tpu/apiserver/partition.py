"""Partitioned control plane: the sharded store/watch fabric.

The single ``ClusterStore`` is the 50k-node wall: every byte of cluster
state flows through ONE lock, one watch fan-out, and (over REST) one
server process. Pathways (arXiv:2203.12533) makes the argument in the
large — past a point, throughput is won not by a faster single
coordinator but by sharding coordination across workers that proceed
asynchronously. This module applies that move to the control plane:

- ``partition_for`` — the ONE routing function (crc32, cross-process
  stable): objects shard by ``(kind, namespace-hash)`` for namespaced
  high-volume kinds (Pod) and by ``(kind, name-hash)`` for cluster-
  scoped high-volume kinds (Node); every other kind lives in partition
  0 so the long-tail API surface needs no fan-out.
- ``PartitionedStore`` — N independent ``ClusterStore`` partitions,
  each with its own lock, WAL segment (``attach_wal``), per-partition
  ``kind_seq`` sequence and latest-committed resourceVersion, behind a
  thin router that preserves today's store API exactly. RVs are
  allocated from ONE shared atomic counter so they stay globally
  unique/comparable; each partition's ``current_rv`` is the newest
  revision IT committed — the per-partition component of the composite
  cursor.
- ``CompositeCursor`` — the per-partition RV vector a list is
  consistent at. List+watch resume is per partition: a watch resumed
  from cursor component p misses nothing partition p committed after
  the list, and a torn stream on one partition relists ONLY that
  partition.
- per-partition **watch dispatch threads** (``async_dispatch=True``):
  a slow/stalled watcher callback on partition A can never delay
  delivery on partition B. Synchronous dispatch (the default) keeps
  ``partitions=1`` behaviorally identical to a bare ``ClusterStore``
  — the differential guard in tests/test_partition.py holds the two
  to identical event sequences, RVs and kind_seq values.
- ``capacity_guard=True`` — the multi-replica scheduler's bind-time
  arbiter: the router (which sees every bind, whichever partition the
  pod lives in) keeps a node-capacity ledger and rejects a bind that
  would oversubscribe a node with ``CapacityConflictError``. The
  losing replica's commit path unreserves/forgets/requeues through
  the PR 3 stale-commit machinery, so two scheduler brains can commit
  concurrently without double-binding a node.

Over REST the same routing function drives the *partition-aware
client* (``client/restcluster.py``): one apiserver process per
partition (each its own GIL — the sharded-coordinator deployment), one
watch stream per (kind, partition), bulk verbs split by partition and
fanned out.
"""

from __future__ import annotations

import contextlib
import queue
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.apiserver.store import ClusterStore, Event

# High-volume kinds that spread across partitions. Namespaced kinds
# shard by (kind, namespace) — the issue key — so one namespace's
# objects stay colocated (list/watch scoped to a namespace touches ONE
# partition); cluster-scoped Node shards by name so heartbeat storms
# and node watch fan-out spread too. Everything else (services, RBAC,
# leases, CRDs, Events, ...) lives in partition 0: correctness for the
# long tail costs zero fan-out code.
SHARDED_NAMESPACED_KINDS = frozenset({"Pod"})
SHARDED_CLUSTER_KINDS = frozenset({"Node"})


def partition_for(kind: str, namespace: Optional[str], name: Optional[str],
                  partitions: int) -> int:
    """The routing function — crc32-based so every process (stores,
    servers, clients, creator children) computes the same shard."""
    if partitions <= 1:
        return 0
    if kind in SHARDED_NAMESPACED_KINDS:
        key = f"{kind}/{namespace or 'default'}"
    elif kind in SHARDED_CLUSTER_KINDS:
        key = f"{kind}/{name or ''}"
    else:
        return 0
    return zlib.crc32(key.encode()) % partitions


def partitions_for(kind: str, partitions: int,
                   namespace: Optional[str] = None) -> List[int]:
    """Which partitions can hold objects of ``kind`` (the list/watch
    fan-out set). A namespace-scoped query on a namespaced sharded kind
    touches exactly one partition."""
    if partitions <= 1:
        return [0]
    if kind in SHARDED_NAMESPACED_KINDS:
        if namespace is not None:
            return [partition_for(kind, namespace, None, partitions)]
        return list(range(partitions))
    if kind in SHARDED_CLUSTER_KINDS:
        return list(range(partitions))
    return [0]


# ---------------------------------------------------------------------------
# elastic topology: the partition layout as a RUNTIME quantity
#
# The static ``partition_for`` hash above fixes the layout at boot — the
# production failure mode at millions-of-users scale is exactly the one
# it cannot answer: one hot namespace saturating its shard while the
# others idle, or a partition process dying outright. The topology layer
# makes placement movable: the sharded keyspace is cut into NUM_SLOTS
# hash slots, each slot owned by a partition, and a migration moves a
# slot (under a bounded freeze-and-drain) without touching the rest of
# the keyspace. ``epoch`` increments on every layout change — clients
# re-route when they observe a newer epoch, and a server that no longer
# owns a slot answers 429 + the new epoch so stale routers converge.

NUM_SLOTS = 64


def slot_for(kind: str, namespace: Optional[str], name: Optional[str],
             slots: int = NUM_SLOTS,
             spread: frozenset = frozenset()) -> Optional[int]:
    """Hash-slot of an object, or None for the pinned long tail
    (everything that is not a sharded kind lives in partition 0 and
    never migrates). Namespaced sharded kinds slot by namespace —
    keeping a namespace colocated — UNLESS the namespace is in
    ``spread``: a namespace the rebalancer has SPLIT slots per object
    name, so one hot tenant's writes fan across every slot (and so
    across every partition) instead of pinning one shard."""
    if kind in SHARDED_NAMESPACED_KINDS:
        ns = namespace or "default"
        key = f"{kind}/{ns}/{name or ''}" if ns in spread \
            else f"{kind}/{ns}"
    elif kind in SHARDED_CLUSTER_KINDS:
        key = f"{kind}/{name or ''}"
    else:
        return None
    return zlib.crc32(key.encode()) % slots


class SliceFrozenError(RuntimeError):
    """A write aimed at a keyspace slice mid-migration outlived the
    freeze budget. Carries the computed ``retry_after`` the REST layer
    surfaces as 429 + Retry-After through the APF envelope."""

    def __init__(self, message: str, retry_after: float = 0.5):
        super().__init__(message)
        self.retry_after = float(retry_after)


class PartitionTopology:
    """The live routing table: ``owner[slot] -> partition`` plus the
    epoch, the spread-namespace set, and (over REST) the partition
    endpoint URLs. Immutable by convention — every layout change builds
    a successor via ``evolve`` with ``epoch + 1`` so observers compare
    a single integer to know whether they are stale."""

    __slots__ = ("partitions", "slots", "owner", "epoch", "spread",
                 "urls", "retired", "replicas")

    def __init__(self, partitions: int, owner: List[int], epoch: int = 1,
                 spread=frozenset(), urls: Optional[List[str]] = None,
                 retired=frozenset(),
                 replicas: Optional[Dict[int, List[str]]] = None):
        self.partitions = int(partitions)
        self.owner: Tuple[int, ...] = tuple(int(o) for o in owner)
        self.slots = len(self.owner)
        self.epoch = int(epoch)
        self.spread = frozenset(spread)
        self.urls = list(urls) if urls is not None else None
        self.retired = frozenset(retired)
        # read-tier advertisement: partition index -> read-replica URLs
        # (apiserver/readtier.py). Replicas serve lists and watches for
        # their partition's keyspace; writes always route to the owner.
        # Empty dict = no read tier (every read hits the owner).
        self.replicas: Dict[int, Tuple[str, ...]] = {
            int(p): tuple(u.rstrip("/") for u in us)
            for p, us in (replicas or {}).items() if us
        }

    @classmethod
    def default(cls, partitions: int, slots: int = NUM_SLOTS,
                urls: Optional[List[str]] = None) -> "PartitionTopology":
        return cls(partitions,
                   [i % max(1, partitions) for i in range(slots)],
                   epoch=1, urls=urls)

    def evolve(self, owner: Optional[List[int]] = None, spread=None,
               partitions: Optional[int] = None,
               urls: Optional[List[str]] = None,
               retired=None, replicas=None) -> "PartitionTopology":
        return PartitionTopology(
            partitions if partitions is not None else self.partitions,
            owner if owner is not None else self.owner,
            epoch=self.epoch + 1,
            spread=self.spread if spread is None else spread,
            urls=self.urls if urls is None else urls,
            retired=self.retired if retired is None else retired,
            replicas=self.replicas if replicas is None else replicas)

    def replicas_for(self, partition: int) -> Tuple[str, ...]:
        return self.replicas.get(int(partition), ())

    # -- routing -------------------------------------------------------
    def slot_of(self, kind: str, namespace: Optional[str],
                name: Optional[str]) -> Optional[int]:
        return slot_for(kind, namespace, name, self.slots, self.spread)

    def partition_of(self, kind: str, namespace: Optional[str],
                     name: Optional[str]) -> int:
        slot = self.slot_of(kind, namespace, name)
        return 0 if slot is None else self.owner[slot]

    def partitions_for(self, kind: str,
                       namespace: Optional[str] = None) -> List[int]:
        if kind in SHARDED_NAMESPACED_KINDS:
            if namespace is not None and namespace not in self.spread:
                return [self.owner[slot_for(kind, namespace, None,
                                            self.slots, self.spread)]]
            return sorted(set(self.owner))
        if kind in SHARDED_CLUSTER_KINDS:
            return sorted(set(self.owner))
        return [0]

    def slots_of_partition(self, partition: int) -> List[int]:
        return [s for s, o in enumerate(self.owner) if o == partition]

    # -- wire ----------------------------------------------------------
    def to_dict(self) -> dict:
        doc = {
            "epoch": self.epoch,
            "partitions": self.partitions,
            "slots": self.slots,
            "owner": list(self.owner),
            "spread": sorted(self.spread),
            "retired": sorted(self.retired),
        }
        if self.urls is not None:
            doc["urls"] = list(self.urls)
        if self.replicas:
            # JSON object keys are strings on the wire; from_dict
            # restores the integer partition indices
            doc["replicas"] = {
                str(p): list(us) for p, us in sorted(self.replicas.items())
            }
        return doc

    @classmethod
    def from_dict(cls, doc: dict) -> "PartitionTopology":
        return cls(int(doc["partitions"]), list(doc["owner"]),
                   epoch=int(doc.get("epoch", 1)),
                   spread=frozenset(doc.get("spread") or ()),
                   urls=doc.get("urls"),
                   retired=frozenset(doc.get("retired") or ()),
                   replicas={int(p): list(us) for p, us in
                             (doc.get("replicas") or {}).items()})

    def __repr__(self) -> str:
        return (f"PartitionTopology(epoch={self.epoch}, "
                f"partitions={self.partitions}, slots={self.slots}, "
                f"spread={sorted(self.spread)})")


class CapacityConflictError(ValueError):
    """A bind that would oversubscribe its target node — the
    multi-replica conflict verdict. Subclasses ValueError so every
    existing bind-failure path (positional ``bind_many`` errors, the
    REST 409 mapping, the scheduler's unreserve/forget/requeue unwind)
    handles it with no new plumbing; the scheduler additionally counts
    it into ``stale_binds_rejected_total{path=bind_conflict}``."""


class CompositeCursor:
    """Per-partition RV vector: the resourceVersion a partitioned list
    is consistent at. Encodes as ``"v0.v1.v2"``; a 1-partition cursor
    encodes as the bare integer so single-partition consumers see
    exactly today's RV strings."""

    __slots__ = ("rvs",)

    def __init__(self, rvs):
        self.rvs: Tuple[int, ...] = tuple(int(v) for v in rvs)

    def encode(self) -> str:
        return ".".join(str(v) for v in self.rvs)

    @classmethod
    def parse(cls, text: str) -> "CompositeCursor":
        return cls(int(p or 0) for p in str(text).split("."))

    def component(self, partition: int) -> int:
        return self.rvs[partition] if partition < len(self.rvs) else 0

    def covers(self, other: "CompositeCursor") -> bool:
        """True when every component is >= the other's — "this list is
        at least as fresh as that one" (resume-safety check)."""
        if len(self.rvs) != len(other.rvs):
            return False
        return all(a >= b for a, b in zip(self.rvs, other.rvs))

    def __eq__(self, other) -> bool:
        return isinstance(other, CompositeCursor) and self.rvs == other.rvs

    def __repr__(self) -> str:
        return f"CompositeCursor({self.encode()})"


class _SharedSeq:
    """The partitions' shared resourceVersion allocator: globally
    unique, monotone, and advanceable past WAL-restored revisions (a
    restored store must never re-issue an RV below what its segments
    already committed)."""

    __slots__ = ("_lock", "_v")

    def __init__(self, start: int = 0):
        self._lock = threading.Lock()
        self._v = int(start)

    def next(self) -> int:
        with self._lock:
            self._v += 1
            return self._v

    def advance_to(self, n: int) -> None:
        with self._lock:
            self._v = max(self._v, int(n))


class _PartitionHandle:
    """Composite watch handle: one underlying registration per
    partition (sync mode) or a subscriber-list entry (async mode)."""

    def __init__(self, stop_fn: Callable[[], None]):
        self._stop_fn = stop_fn

    def stop(self) -> None:
        self._stop_fn()


class _Dispatcher:
    """One partition's watch dispatch thread: events enqueue under the
    partition lock (cheap append + notify) and fan out to subscribers
    on THIS thread — a watcher that blocks here stalls only this
    partition's deliveries, never a sibling's."""

    def __init__(self, index: int, subscribers_fn):
        self.index = index
        self._subscribers_fn = subscribers_fn
        self._q: "queue.Queue[Optional[List[Event]]]" = queue.Queue()
        # pending batches counted under a condition (not an Event off
        # the queue's emptiness: submit() enqueues after any emptiness
        # check the worker could make, so drain() must wait on a
        # counter that is incremented BEFORE the put and decremented
        # only after delivery completed)
        self._cond = threading.Condition()
        self._pending = 0
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"partition-dispatch-{index}")
        self._thread.start()

    def submit(self, events: List[Event]) -> None:
        with self._cond:
            self._pending += 1
        self._q.put(events)

    def _run(self) -> None:
        while True:
            events = self._q.get()
            if events is None:
                return
            try:
                for fn, batch_fn in self._subscribers_fn():
                    try:
                        if batch_fn is not None:
                            batch_fn(events)
                        else:
                            for e in events:
                                fn(e)
                    except Exception:  # noqa: BLE001 — one bad watcher
                        # must not kill the partition's dispatch thread
                        pass
            finally:
                with self._cond:
                    self._pending -= 1
                    if self._pending == 0:
                        self._cond.notify_all()

    def drain(self, timeout: float = 10.0) -> bool:
        with self._cond:
            return self._cond.wait_for(
                lambda: self._pending == 0, timeout)

    def stop(self) -> None:
        self._q.put(None)
        self._thread.join(timeout=2.0)


class _BindLedger:
    """Node-capacity arbiter for concurrent scheduler replicas. The
    router sees EVERY bind (the pod's partition serializes same-pod
    races; this ledger serializes same-node capacity races across
    partitions): reserve-then-bind, release on store rejection, so two
    brains committing simultaneously cannot jointly exceed a node's
    allocatable. Tracks milli-CPU + memory, the two axes every bench
    workload requests."""

    def __init__(self):
        self._lock = threading.Lock()
        self._alloc: Dict[str, Tuple[int, int]] = {}
        self._used: Dict[str, List[int]] = {}
        self._pod_req: Dict[str, Tuple[str, int, int]] = {}

    @staticmethod
    def _pod_request(pod) -> Tuple[int, int]:
        milli = mem = 0
        for c in pod.spec.containers:
            req = c.resources.requests
            q = req.get("cpu")
            if q is not None:
                milli += int(q.milli_value())
            q = req.get("memory")
            if q is not None:
                mem += int(q.value())
        return milli, mem

    def note_node(self, node) -> None:
        alloc = node.status.allocatable or node.status.capacity or {}
        cpu = alloc.get("cpu")
        mem = alloc.get("memory")
        with self._lock:
            self._alloc[node.name] = (
                int(cpu.milli_value()) if cpu is not None else 1 << 62,
                int(mem.value()) if mem is not None else 1 << 62,
            )

    def drop_node(self, name: str) -> None:
        with self._lock:
            self._alloc.pop(name, None)

    # reserve() verdicts: the caller must know whether THIS call
    # charged the ledger — a failed bind may only release its OWN
    # reservation, never a concurrent winner's (releasing on a same-pod
    # CAS loss would silently leak the winner's capacity)
    CONFLICT = 0
    CHARGED = 1
    KEPT = 2

    def reserve(self, key: str, pod, node_name: str) -> int:
        """Charge the pod against the node. ``CONFLICT`` = would
        oversubscribe (the bind must be refused); ``CHARGED`` = this
        call took the reservation (release it if the bind fails);
        ``KEPT`` = an earlier reservation (possibly a racing sibling's)
        already covers the pod — not this call's to release. Unknown
        nodes are not judged — the store deliberately accepts binds
        into the void (PR 3's guards own that failure mode)."""
        milli, mem = self._pod_request(pod)
        with self._lock:
            if key in self._pod_req:
                return self.KEPT
            alloc = self._alloc.get(node_name)
            if alloc is None:
                self._pod_req[key] = (node_name, milli, mem)
                return self.CHARGED
            used = self._used.setdefault(node_name, [0, 0])
            if used[0] + milli > alloc[0] or used[1] + mem > alloc[1]:
                return self.CONFLICT
            used[0] += milli
            used[1] += mem
            self._pod_req[key] = (node_name, milli, mem)
            return self.CHARGED

    def release(self, key: str, node_name: Optional[str] = None) -> None:
        """Drop the pod's reservation. With ``node_name`` given, only a
        reservation AGAINST THAT NODE is dropped — a losing bind must
        release exactly the charge it took, never one a racing sibling
        has since re-pointed to the node that actually won (confirm())."""
        with self._lock:
            got = self._pod_req.get(key)
            if got is None:
                return
            if node_name is not None and got[0] != node_name:
                return
            del self._pod_req[key]
            rec_node, milli, mem = got
            used = self._used.get(rec_node)
            if used is not None:
                used[0] -= milli
                used[1] -= mem

    def confirm(self, key: str, pod, node_name: str) -> None:
        """Align the ledger with a bind the store COMMITTED: whatever
        was reserved (possibly against a different node by a racing
        sibling whose target lost), the pod now occupies ``node_name``
        — charge it there unconditionally (committed truth outranks
        the budget; the guard's job was before the commit)."""
        milli, mem = self._pod_request(pod)
        with self._lock:
            got = self._pod_req.get(key)
            if got is not None:
                if got[0] == node_name:
                    return
                rec_node, r_milli, r_mem = got
                used = self._used.get(rec_node)
                if used is not None:
                    used[0] -= r_milli
                    used[1] -= r_mem
            used = self._used.setdefault(node_name, [0, 0])
            used[0] += milli
            used[1] += mem
            self._pod_req[key] = (node_name, milli, mem)


class PartitionedStore:
    """N independent store partitions behind today's ``ClusterStore``
    API. See the module docstring for the design; the router's job is
    purely mechanical — route single-object calls by ``partition_for``,
    fan list calls in, group bulk calls by partition, and keep the
    long tail (every non-sharded kind) on partition 0 so the untouched
    surface delegates via ``__getattr__``."""

    def __init__(self, partitions: int = 4, async_dispatch: bool = False,
                 capacity_guard: bool = False,
                 store_factory: Callable[..., ClusterStore] = ClusterStore,
                 topology: Optional[PartitionTopology] = None,
                 reshardable: bool = False,
                 evict_grace_s: float = 0.25):
        if partitions < 1:
            raise ValueError("partitions must be >= 1")
        self.partitions = int(partitions)
        self._store_factory = store_factory
        self._rv_seq = _SharedSeq()
        self.parts: List[ClusterStore] = [
            store_factory(rv_source=self._rv_seq.next)
            for _ in range(self.partitions)
        ]
        self._subs_lock = threading.Lock()
        self._subs: List[Tuple[Callable, Optional[Callable]]] = []
        # sync-mode watcher registry: add_partition must re-register
        # every live watcher on the new partition (they subscribed to
        # the fleet, not to an index list frozen at boot)
        self._sync_watches: List[dict] = []
        self.async_dispatch = bool(async_dispatch)
        self._dispatchers: List[_Dispatcher] = []
        self._part_handles: List = []
        if self.async_dispatch:
            for i, part in enumerate(self.parts):
                self._attach_dispatcher(i, part)
        self.ledger = _BindLedger() if capacity_guard else None
        self._wals: List[Any] = []
        self._wal_dir: Optional[str] = None
        self._wal_kwargs: dict = {}
        self._watch_caches: Optional[List[Any]] = None
        # -- elastic layer (None topology = PR 9's static routing,
        # byte-identical; the differential guard depends on it) --------
        if topology is None and reshardable:
            topology = PartitionTopology.default(self.partitions)
        self.topology = topology
        self._reshard_lock = threading.Lock()
        self._freeze_cond = threading.Condition()
        self._frozen: Dict[int, float] = {}      # slot -> deadline (mono)
        self.slot_writes: Dict[int, int] = {}
        self.ns_writes: Dict[str, int] = {}
        self.migrations: List[dict] = []
        self.evict_grace_s = float(evict_grace_s)

    def _attach_dispatcher(self, index: int, part: ClusterStore) -> None:
        disp = _Dispatcher(index, self._subscribers)
        self._dispatchers.append(disp)
        self._part_handles.append(part.watch(
            lambda e, d=disp: d.submit([e]),
            batch_fn=lambda evs, d=disp: d.submit(list(evs)),
        ))

    # -- routing -------------------------------------------------------
    def _p(self, kind: str, namespace: Optional[str] = None,
           name: Optional[str] = None) -> ClusterStore:
        topo = self.topology
        if topo is not None:
            return self.parts[topo.partition_of(kind, namespace, name)]
        return self.parts[partition_for(kind, namespace, name,
                                        self.partitions)]

    def _fan(self, kind: str, namespace: Optional[str] = None
             ) -> List[ClusterStore]:
        topo = self.topology
        if topo is not None:
            return [self.parts[i]
                    for i in topo.partitions_for(kind, namespace)]
        return [self.parts[i]
                for i in partitions_for(kind, self.partitions, namespace)]

    # -- elastic routing: freeze-aware, flip-safe write/read paths -----
    def _wait_unfrozen(self, slot: Optional[int]) -> None:
        """Block while ``slot`` is inside a migration's freeze window
        (bounded: the window carries a deadline; a migration that dies
        auto-thaws). Raises ``SliceFrozenError`` with a computed
        retry-after only when the budget is exhausted — in the normal
        case a frozen write PAUSES briefly and lands on the new owner,
        invisible to the caller but for latency."""
        if slot is None or not self._frozen:
            return
        with self._freeze_cond:
            while True:
                deadline = self._frozen.get(slot)
                if deadline is None:
                    return
                now = time.monotonic()
                if now >= deadline:
                    # auto-thaw: a crashed migration must not freeze a
                    # slice forever (the rollback path unfreezes; this
                    # is the backstop)
                    self._frozen.pop(slot, None)
                    self._freeze_cond.notify_all()
                    return
                if not self._freeze_cond.wait(timeout=deadline - now):
                    remaining = self._frozen.get(slot)
                    if remaining is not None \
                            and time.monotonic() < remaining:
                        raise SliceFrozenError(
                            f"slot {slot} frozen by a live migration",
                            retry_after=max(
                                0.05, remaining - time.monotonic()))

    def _note_write(self, slot: Optional[int],
                    namespace: Optional[str]) -> None:
        # per-slot / per-namespace write ledger: the rebalancer's
        # hotspot signal (dict ops are GIL-atomic enough for a load
        # estimate; the ledger informs decisions, never correctness)
        if slot is not None:
            self.slot_writes[slot] = self.slot_writes.get(slot, 0) + 1
            if namespace is not None:
                self.ns_writes[namespace] = \
                    self.ns_writes.get(namespace, 0) + 1

    def _one_write(self, kind: str, namespace: Optional[str],
                   name: Optional[str], fn: Callable[[ClusterStore], Any]):
        """Route one mutation. Static mode is a plain dispatch; in
        topology mode the write re-validates its route UNDER the target
        partition's lock — a migration that flipped the slot while this
        writer waited on the lock re-routes it to the new owner instead
        of committing into an evicted slice (the torn-write race a
        check-then-act router would have)."""
        topo = self.topology
        if topo is None:
            return fn(self._p(kind, namespace, name))
        while True:
            slot = topo.slot_of(kind, namespace, name)
            self._wait_unfrozen(slot)
            part = self.parts[0 if slot is None else topo.owner[slot]]
            with part._lock:
                cur = self.topology
                cur_slot = cur.slot_of(kind, namespace, name)
                if (self.parts[0 if cur_slot is None
                               else cur.owner[cur_slot]] is part
                        and cur_slot not in self._frozen):
                    self._note_write(cur_slot, namespace
                                     if kind in SHARDED_NAMESPACED_KINDS
                                     else None)
                    return fn(part)
            topo = self.topology   # flipped under us: re-route

    def _one_read(self, kind: str, namespace: Optional[str],
                  name: Optional[str], fn: Callable[[ClusterStore], Any]):
        """Route one read, flip-safe (reads never block on a freeze —
        the source keeps serving until the flip, the destination
        after)."""
        topo = self.topology
        if topo is None:
            return fn(self._p(kind, namespace, name))
        while True:
            part = self.parts[topo.partition_of(kind, namespace, name)]
            with part._lock:
                cur = self.topology
                if self.parts[cur.partition_of(kind, namespace,
                                               name)] is part:
                    return fn(part)
            topo = self.topology

    def _bulk_write(self, kind: str, items: List[Any], key_of,
                    fn: Callable[[ClusterStore, List[Tuple[int, Any]]],
                                 None]) -> None:
        """Bulk mutation split by partition with the same flip-safety
        as ``_one_write``: each group re-validates every member's route
        under its partition lock; members a concurrent migration moved
        re-group and retry on the new owner. ``fn(part, [(index, item),
        ...])`` applies one group."""
        pending: List[Tuple[int, Any]] = list(enumerate(items))
        while pending:
            topo = self.topology
            groups: Dict[int, List[Tuple[int, Any]]] = {}
            for i, item in pending:
                ns, name = key_of(item)
                slot = topo.slot_of(kind, ns, name)
                self._wait_unfrozen(slot)
                groups.setdefault(
                    0 if slot is None else topo.owner[slot],
                    []).append((i, item))
            pending = []
            for p, group in groups.items():
                part = self.parts[p]
                with part._lock:
                    cur = self.topology
                    keep: List[Tuple[int, Any]] = []
                    for i, item in group:
                        ns, name = key_of(item)
                        slot = cur.slot_of(kind, ns, name)
                        owner = 0 if slot is None else cur.owner[slot]
                        if self.parts[owner] is part \
                                and slot not in self._frozen:
                            keep.append((i, item))
                            self._note_write(
                                slot, ns if kind in
                                SHARDED_NAMESPACED_KINDS else None)
                        else:
                            pending.append((i, item))
                    if keep:
                        fn(part, keep)

    def __getattr__(self, name: str):
        # the non-sharded long tail (services, RBAC, PV/PVC, CRDs,
        # leases, log/exec sources, ...) lives wholly in partition 0 —
        # its untouched ClusterStore surface IS the implementation
        if name.startswith("_") or name == "parts":
            raise AttributeError(name)
        return getattr(object.__getattribute__(self, "parts")[0], name)

    # event_ttl is a plain attribute on ClusterStore; writes must reach
    # partition 0 (where Events live), not shadow it on the router
    @property
    def event_ttl(self) -> float:
        return self.parts[0].event_ttl

    @event_ttl.setter
    def event_ttl(self, value: float) -> None:
        self.parts[0].event_ttl = value

    # -- watches -------------------------------------------------------
    def _subscribers(self) -> List[Tuple[Callable, Optional[Callable]]]:
        with self._subs_lock:
            return list(self._subs)

    def watch(self, fn: Callable[[Event], None],
              batch_fn: Optional[Callable[[List[Event]], None]] = None):
        if self.async_dispatch:
            entry = (fn, batch_fn)
            with self._subs_lock:
                self._subs.append(entry)

            def stop() -> None:
                with self._subs_lock:
                    if entry in self._subs:
                        self._subs.remove(entry)

            return _PartitionHandle(stop)
        rec = {"fn": fn, "batch_fn": batch_fn,
               "handles": [p.watch(fn, batch_fn) for p in self.parts]}
        with self._subs_lock:
            self._sync_watches.append(rec)

        def stop_sync(rec=rec) -> None:
            with self._subs_lock:
                if rec in self._sync_watches:
                    self._sync_watches.remove(rec)
            for h in rec["handles"]:
                h.stop()

        return _PartitionHandle(stop_sync)

    def drain(self, timeout: float = 10.0) -> bool:
        """Block until every partition's dispatch queue is empty (async
        mode; tests and quiesce barriers)."""
        return all(d.drain(timeout) for d in self._dispatchers)

    def stop(self) -> None:
        for h in self._part_handles:
            h.stop()
        for d in self._dispatchers:
            d.stop()
        for wal in self._wals:
            with contextlib.suppress(Exception):
                wal.close()

    # -- resume (composite cursor) -------------------------------------
    def enable_resume(self, capacity: int = 100_000) -> None:
        """Attach one revisioned watch cache per partition — the
        replay half of list+watch resume (``watch_from_cursor``)."""
        if self._watch_caches is None:
            from kubernetes_tpu.apiserver.watchcache import WatchCache

            self._watch_caches = [WatchCache(p, capacity=capacity)
                                  for p in self.parts]

    def cursor(self) -> CompositeCursor:
        """The store's current composite cursor (one component per
        partition: the newest revision that partition committed)."""
        return CompositeCursor(p.current_rv() for p in self.parts)

    def list_with_cursor(self, kind: str,
                         namespace: Optional[str] = None
                         ) -> Tuple[List[Any], CompositeCursor]:
        """List + the composite cursor the list is consistent at: a
        per-partition watch resumed from component p misses nothing
        partition p committed after its slice of this list."""
        objs: List[Any] = []
        rvs = [p.current_rv() for p in self.parts]
        for i in partitions_for(kind, self.partitions, namespace):
            got, rv = self.parts[i].list_objects_with_rv(kind, namespace)
            objs.extend(got)
            rvs[i] = rv
        return objs, CompositeCursor(rvs)

    def watch_from_cursor(self, cursor: CompositeCursor,
                          fn: Callable[[int, Event], None]):
        """Resume watching from a composite cursor: per partition,
        replay everything committed after the cursor component, then
        stream live (``enable_resume`` must have been called before the
        cursor was taken). A component that has been compacted out
        raises ``TooOldResourceVersion`` — the caller relists THAT
        partition only."""
        if self._watch_caches is None:
            raise RuntimeError("enable_resume() was never called")
        handles = []
        try:
            for i, cache in enumerate(self._watch_caches):
                handles.append(cache.watch_from(cursor.component(i), fn))
        except Exception:
            for h in handles:
                h.stop()
            raise
        return _PartitionHandle(lambda: [h.stop() for h in handles])

    # -- durability ----------------------------------------------------
    def attach_wal(self, wal_dir: str, restore: bool = False,
                   **kwargs) -> List[Any]:
        """One WAL segment per partition (``<dir>/p<k>/wal.jsonl``):
        partitions serialize their own mutations, so segments append
        with zero cross-partition contention and restore in any order.
        ``restore=True`` first replays each partition's snapshot+log
        (crash recovery) and advances the shared RV allocator past
        every restored revision — a recovered store must never re-issue
        a committed RV."""
        import os

        from kubernetes_tpu.apiserver.wal import attach_wal, restore_store

        self._wal_dir = wal_dir
        self._wal_kwargs = dict(kwargs)
        for i, part in enumerate(self.parts):
            seg = os.path.join(wal_dir, f"p{i}")
            os.makedirs(seg, exist_ok=True)
            if restore:
                restore_store(seg, part)
            self._wals.append(attach_wal(part, seg, **kwargs))
        self._rv_seq.advance_to(max(p.current_rv() for p in self.parts))
        return list(self._wals)

    # -- observability -------------------------------------------------
    def partition_registries(self):
        """One tiny metrics registry per partition (scraped by the
        scale harness through the PR 8 federation as
        ``instance=partition-<k>``): latest committed RV, object
        count, and cumulative kind_seq mutations."""
        from kubernetes_tpu.metrics.registry import Gauge, MetricsRegistry

        out = []
        for i, part in enumerate(self.parts):
            reg = MetricsRegistry()
            rv = Gauge("partition_resource_version",
                       "Newest revision this partition committed")
            objs = Gauge("partition_objects",
                         "Objects resident in this partition")
            muts = Gauge("partition_mutations_total",
                         "Cumulative per-kind mutation count")
            reg.register(rv)
            reg.register(objs)
            reg.register(muts)
            rv.set(float(part.current_rv()))
            with part._lock:
                objs.set(float(sum(
                    len(getattr(part, attr))
                    for attr, _ in part._KIND_TABLES.values())))
                muts.set(float(sum(part._kind_seq.values())))
            out.append(reg)
        return out

    # -- pods ----------------------------------------------------------
    def create_pod(self, pod):
        created = self._one_write(
            "Pod", pod.namespace, pod.metadata.name,
            lambda part: part.create_pod(pod))
        if self.ledger is not None and pod.spec.node_name:
            self.ledger.reserve(pod.full_name(), pod, pod.spec.node_name)
        return created

    def create_pods(self, pods):
        if self.topology is not None:
            self._bulk_write(
                "Pod", pods,
                lambda p: (p.namespace, p.metadata.name),
                lambda part, group: part.create_pods(
                    [p for _, p in group]))
        else:
            by_part: Dict[ClusterStore, list] = {}
            for pod in pods:
                by_part.setdefault(self._p("Pod", pod.namespace),
                                   []).append(pod)
            for part, group in by_part.items():
                part.create_pods(group)
        if self.ledger is not None:
            for pod in pods:
                if pod.spec.node_name:
                    self.ledger.reserve(pod.full_name(), pod,
                                        pod.spec.node_name)
        return pods

    def bind(self, namespace: str, name: str, uid: str,
             node_name: str) -> None:
        def run(part: ClusterStore) -> None:
            key = f"{namespace}/{name}"
            charged = False
            pod = None
            if self.ledger is not None:
                pod = part.get_pod(namespace, name)
                if pod is not None and not pod.spec.node_name:
                    verdict = self.ledger.reserve(key, pod, node_name)
                    if verdict == _BindLedger.CONFLICT:
                        raise CapacityConflictError(
                            f"pod {key}: capacity conflict on node "
                            f"{node_name!r} (concurrent replica won the "
                            f"remaining capacity)")
                    charged = verdict == _BindLedger.CHARGED
            try:
                part.bind(namespace, name, uid, node_name)
            except Exception:
                # release ONLY the reservation this call took (keyed to
                # its own node): on a same-pod CAS loss the surviving
                # charge — possibly already re-pointed by the winner's
                # confirm — belongs to the winner
                if charged:
                    self.ledger.release(key, node_name)
                raise
            if self.ledger is not None and pod is not None:
                # the store committed THIS node: align the ledger even
                # when a racing sibling reserved the pod against a
                # different target first (committed truth outranks the
                # reservation)
                self.ledger.confirm(key, pod, node_name)

        self._one_write("Pod", namespace, name, run)

    def _bind_group(self, part: ClusterStore, group, errors) -> None:
        """One partition's slice of a bulk bind: ledger precheck, bulk
        bind, per-item ledger settlement — shared by the static and
        topology-routed paths."""
        todo = []
        for i, b in group:
            namespace, name, uid, node_name = b
            charged = False
            pod = None
            if self.ledger is not None:
                key = f"{namespace}/{name}"
                pod = part.get_pod(namespace, name)
                if pod is not None and not pod.spec.node_name:
                    verdict = self.ledger.reserve(key, pod, node_name)
                    if verdict == _BindLedger.CONFLICT:
                        errors[i] = CapacityConflictError(
                            f"pod {key}: capacity conflict on node "
                            f"{node_name!r} (concurrent replica won "
                            f"the remaining capacity)")
                        continue
                    charged = verdict == _BindLedger.CHARGED
            todo.append((i, b, charged, pod))
        got = part.bind_many([b for _, b, _, _ in todo])
        for (i, b, charged, pod), err in zip(todo, got):
            errors[i] = err
            if self.ledger is None:
                continue
            key = f"{b[0]}/{b[1]}"
            if err is not None:
                # as in bind(): only this call's own reservation,
                # keyed to its own node
                if charged:
                    self.ledger.release(key, b[3])
            elif pod is not None:
                self.ledger.confirm(key, pod, b[3])

    def bind_many(self, bindings):
        errors: List[Optional[Exception]] = [None] * len(bindings)
        if self.topology is not None:
            self._bulk_write(
                "Pod", list(bindings), lambda b: (b[0], b[1]),
                lambda part, group: self._bind_group(part, group, errors))
            return errors
        by_part: Dict[ClusterStore, list] = {}
        for i, b in enumerate(bindings):
            by_part.setdefault(self._p("Pod", b[0]), []).append((i, b))
        for part, group in by_part.items():
            self._bind_group(part, group, errors)
        return errors

    def update_pod(self, pod):
        return self._one_write("Pod", pod.namespace, pod.metadata.name,
                               lambda part: part.update_pod(pod))

    def delete_pod(self, namespace: str, name: str) -> None:
        if self.ledger is not None:
            self.ledger.release(f"{namespace}/{name}")
        self._one_write("Pod", namespace, name,
                        lambda part: part.delete_pod(namespace, name))

    def delete_pods(self, keys) -> None:
        if self.ledger is not None:
            for namespace, name in keys:
                self.ledger.release(f"{namespace}/{name}")
        if self.topology is not None:
            self._bulk_write(
                "Pod", list(keys), lambda k: (k[0], k[1]),
                lambda part, group: part.delete_pods(
                    [k for _, k in group]))
            return
        by_part: Dict[ClusterStore, list] = {}
        for namespace, name in keys:
            by_part.setdefault(self._p("Pod", namespace),
                               []).append((namespace, name))
        for part, group in by_part.items():
            part.delete_pods(group)

    def get_pod(self, namespace: str, name: str):
        return self._one_read("Pod", namespace, name,
                              lambda part: part.get_pod(namespace, name))

    def list_pods(self, namespace: Optional[str] = None):
        out: List[Any] = []
        for part in self._fan("Pod", namespace):
            out.extend(part.list_pods(namespace))
        return out

    def patch_pod_condition(self, namespace: str, name: str,
                            condition) -> None:
        self._one_write("Pod", namespace, name,
                        lambda part: part.patch_pod_condition(
                            namespace, name, condition))

    def set_nominated_node_name(self, namespace: str, name: str,
                                node: str) -> None:
        self._one_write("Pod", namespace, name,
                        lambda part: part.set_nominated_node_name(
                            namespace, name, node))

    def clear_nominated_node_name(self, namespace: str, name: str) -> None:
        self._one_write("Pod", namespace, name,
                        lambda part: part.clear_nominated_node_name(
                            namespace, name))

    def set_pod_phase(self, namespace: str, name: str, phase: str,
                      pod_ip: str = "", host_ip: str = "") -> bool:
        return self._one_write(
            "Pod", namespace, name,
            lambda part: part.set_pod_phase(namespace, name, phase,
                                            pod_ip, host_ip))

    def batched_status_writes(self):
        return contextlib.nullcontext()

    # -- nodes ---------------------------------------------------------
    def add_node(self, node) -> None:
        if self.ledger is not None:
            self.ledger.note_node(node)
        self._one_write("Node", None, node.name,
                        lambda part: part.add_node(node))

    def update_node(self, node) -> None:
        if self.ledger is not None:
            self.ledger.note_node(node)
        self._one_write("Node", None, node.name,
                        lambda part: part.update_node(node))

    def delete_node(self, name: str) -> None:
        if self.ledger is not None:
            self.ledger.drop_node(name)
        self._one_write("Node", None, name,
                        lambda part: part.delete_node(name))

    def get_node(self, name: str):
        return self._one_read("Node", None, name,
                              lambda part: part.get_node(name))

    def list_nodes(self):
        out: List[Any] = []
        for part in self._fan("Node"):
            out.extend(part.list_nodes())
        return out

    # -- generic typed-object surface ----------------------------------
    def kind_seq(self, kind: str) -> int:
        return sum(p.kind_seq(kind)
                   for p in self._fan(kind))

    def current_rv(self) -> int:
        return max(p.current_rv() for p in self.parts)

    def known_kinds(self):
        return self.parts[0].known_kinds()

    def kind_is_namespaced(self, kind: str) -> bool:
        return self.parts[0].kind_is_namespaced(kind)

    def create_object(self, kind: str, obj):
        if self.ledger is not None and kind == "Node":
            self.ledger.note_node(obj)
        return self._one_write(
            kind, obj.metadata.namespace, obj.metadata.name,
            lambda part: part.create_object(kind, obj))

    def create_objects_bulk(self, kind: str, objs) -> int:
        if self.ledger is not None and kind == "Node":
            for obj in objs:
                self.ledger.note_node(obj)
        if self.topology is not None:
            created = [0]

            def run(part, group):
                created[0] += part.create_objects_bulk(
                    kind, [o for _, o in group])

            self._bulk_write(
                kind, list(objs),
                lambda o: (o.metadata.namespace, o.metadata.name), run)
            return created[0]
        by_part: Dict[ClusterStore, list] = {}
        for obj in objs:
            by_part.setdefault(
                self._p(kind, obj.metadata.namespace, obj.metadata.name),
                []).append(obj)
        return sum(part.create_objects_bulk(kind, group)
                   for part, group in by_part.items())

    def update_object(self, kind: str, obj, expect_rv=None):
        return self._one_write(
            kind, obj.metadata.namespace, obj.metadata.name,
            lambda part: part.update_object(kind, obj,
                                            expect_rv=expect_rv))

    def delete_object(self, kind: str, namespace: str, name: str) -> bool:
        return self._one_write(
            kind, namespace, name,
            lambda part: part.delete_object(kind, namespace, name))

    def get_object(self, kind: str, namespace: str, name: str):
        return self._one_read(
            kind, namespace, name,
            lambda part: part.get_object(kind, namespace, name))

    def mutate_object(self, kind: str, namespace: str, name: str,
                      mutate, retries: int = 8):
        return self._one_write(
            kind, namespace, name,
            lambda part: part.mutate_object(kind, namespace, name,
                                            mutate, retries=retries))

    def add_finalizer(self, kind: str, namespace: str, name: str,
                      finalizer: str) -> bool:
        return self._one_write(
            kind, namespace, name,
            lambda part: part.add_finalizer(kind, namespace, name,
                                            finalizer))

    def remove_finalizer(self, kind: str, namespace: str, name: str,
                         finalizer: str) -> bool:
        return self._one_write(
            kind, namespace, name,
            lambda part: part.remove_finalizer(kind, namespace, name,
                                               finalizer))

    def list_objects(self, kind: str,
                     namespace: Optional[str] = None):
        return self.list_objects_with_rv(kind, namespace)[0]

    def list_objects_with_rv(self, kind: str,
                             namespace: Optional[str] = None):
        objs: List[Any] = []
        rv = 0
        for part in self._fan(kind, namespace):
            got, part_rv = part.list_objects_with_rv(kind, namespace)
            objs.extend(got)
            rv = max(rv, part_rv)
        return objs, rv

    # ------------------------------------------------------------------
    # live resharding: split / merge / move under a bounded freeze
    #
    # Protocol (one migration at a time, serialized by _reshard_lock):
    #   1. FREEZE the moving slots (writers pause on a condition, budget-
    #      bounded; readers keep flowing).
    #   2. Under ALL partition locks: copy every affected object to its
    #      new owner via the SILENT adopt channel (RVs preserved, no
    #      watch events — consumers already hold this state), then FLIP
    #      the topology (epoch + 1). Lists/gets serialize against the
    #      flip on the partition locks; the routed write/read wrappers
    #      re-validate after the flip.
    #   3. Unfreeze (writers resume against the new owner).
    #   4. After a short grace (so an in-flight fan-in list that chose
    #      its partition set pre-flip still finds the objects — dict-
    #      keyed consumers collapse the transient duplicate), EVICT the
    #      source copies silently.
    # Zero watch events are lost or duplicated: pre-flip events were
    # delivered from the source partition's stream, post-flip events
    # dispatch from the destination, and the seam itself is silent.

    def _require_topology(self) -> PartitionTopology:
        if self.topology is None:
            raise RuntimeError(
                "live resharding requires a topology "
                "(PartitionedStore(reshardable=True))")
        return self.topology

    def _live_partitions(self) -> List[int]:
        topo = self._require_topology()
        return [i for i in range(len(self.parts))
                if i not in topo.retired]

    def _migrate(self, new_topo: PartitionTopology,
                 freeze_slots: List[int], scan_parts: List[int],
                 freeze_budget_s: float, reason: str) -> dict:
        t0 = time.monotonic()
        with self._freeze_cond:
            deadline = time.monotonic() + freeze_budget_s
            for s in freeze_slots:
                self._frozen[s] = deadline
        moved = 0
        rv_barrier = 0
        evictions: List[Tuple[int, str, List[Tuple[str, str]]]] = []
        try:
            with contextlib.ExitStack() as stack:
                for part in self.parts:
                    stack.enter_context(part._lock)
                rv_barrier = max(p.current_rv() for p in self.parts)
                for src in scan_parts:
                    src_part = self.parts[src]
                    for kind in (tuple(SHARDED_NAMESPACED_KINDS)
                                 + tuple(SHARDED_CLUSTER_KINDS)):
                        attr, _ns = ClusterStore._KIND_TABLES[kind]
                        groups: Dict[int, List[Any]] = {}
                        for obj in getattr(src_part, attr).values():
                            dest = new_topo.partition_of(
                                kind, obj.metadata.namespace,
                                obj.metadata.name)
                            if dest != src:
                                groups.setdefault(dest, []).append(obj)
                        for dest, objs in groups.items():
                            self.parts[dest].adopt_objects(kind, objs)
                            moved += len(objs)
                            evictions.append((src, kind, [
                                (o.metadata.namespace, o.metadata.name)
                                for o in objs]))
                self.topology = new_topo
        finally:
            with self._freeze_cond:
                for s in freeze_slots:
                    self._frozen.pop(s, None)
                self._freeze_cond.notify_all()
        frozen_ms = (time.monotonic() - t0) * 1000.0
        if evictions:
            if self.evict_grace_s > 0:
                time.sleep(self.evict_grace_s)
            for src, kind, keys in evictions:
                self.parts[src].evict_objects(kind, keys)
        report = {
            "reason": reason,
            "epoch": new_topo.epoch,
            "moved_objects": moved,
            "frozen_slots": sorted(freeze_slots),
            "frozen_ms": round(frozen_ms, 3),
            "rv_barrier": rv_barrier,
        }
        self.migrations.append(report)
        return report

    def migrate_slots(self, assignments: Dict[int, int],
                      freeze_budget_s: float = 5.0) -> dict:
        """MOVE: reassign hash slots to new owner partitions
        (``{slot: dest_partition}``) under the freeze-and-drain
        protocol. Everything outside the moving slots stays hot."""
        with self._reshard_lock:
            topo = self._require_topology()
            owner = list(topo.owner)
            srcs = set()
            for slot, dest in assignments.items():
                if dest >= len(self.parts) or dest in topo.retired:
                    raise ValueError(f"bad destination partition {dest}")
                if owner[slot] != dest:
                    srcs.add(owner[slot])
                    owner[slot] = int(dest)
            if not srcs:
                return {"reason": "move", "epoch": topo.epoch,
                        "moved_objects": 0, "frozen_slots": [],
                        "frozen_ms": 0.0, "rv_barrier": 0}
            return self._migrate(
                topo.evolve(owner=owner),
                sorted(assignments), sorted(srcs),
                freeze_budget_s, "move")

    def spread_namespace(self, namespace: str,
                         freeze_budget_s: float = 5.0) -> dict:
        """SPLIT: a hot namespace stops slotting as one unit — its
        objects re-slot by (namespace, name), fanning one tenant's
        keyspace across every slot and so across every partition. The
        namespace's old slot freezes for the drain; everything else
        stays hot."""
        with self._reshard_lock:
            topo = self._require_topology()
            if namespace in topo.spread:
                return {"reason": "split", "epoch": topo.epoch,
                        "moved_objects": 0, "frozen_slots": [],
                        "frozen_ms": 0.0, "rv_barrier": 0}
            old_slot = topo.slot_of("Pod", namespace, None)
            src = topo.owner[old_slot]
            return self._migrate(
                topo.evolve(spread=topo.spread | {namespace}),
                [old_slot], [src], freeze_budget_s, "split")

    def retire_partition(self, index: int,
                         freeze_budget_s: float = 5.0) -> dict:
        """MERGE: drain a partition — every slot it owns migrates to
        the remaining live partitions (round-robin) and the partition
        is marked retired (it receives no further traffic; its process
        can be torn down)."""
        with self._reshard_lock:
            topo = self._require_topology()
            remaining = [i for i in self._live_partitions() if i != index]
            if not remaining:
                raise ValueError("cannot retire the last live partition")
            owner = list(topo.owner)
            moving = [s for s, o in enumerate(owner) if o == index]
            for k, slot in enumerate(moving):
                owner[slot] = remaining[k % len(remaining)]
            return self._migrate(
                topo.evolve(owner=owner,
                            retired=topo.retired | {index}),
                moving, [index], freeze_budget_s, "merge")

    def add_partition(self) -> int:
        """Grow the fleet by one (empty) partition — the control-plane
        autoscaler's buy. Slots migrate to it separately
        (``migrate_slots``), so the buy itself is instant."""
        with self._reshard_lock:
            topo = self._require_topology()
            idx = len(self.parts)
            part = self._store_factory(rv_source=self._rv_seq.next)
            if self._wal_dir is not None:
                import os

                from kubernetes_tpu.apiserver.wal import attach_wal

                seg = os.path.join(self._wal_dir, f"p{idx}")
                os.makedirs(seg, exist_ok=True)
                self._wals.append(attach_wal(part, seg,
                                             **self._wal_kwargs))
            with self._subs_lock:
                for rec in self._sync_watches:
                    rec["handles"].append(
                        part.watch(rec["fn"], rec["batch_fn"]))
            if self._watch_caches is not None:
                from kubernetes_tpu.apiserver.watchcache import WatchCache

                self._watch_caches.append(WatchCache(part))
            self.parts.append(part)
            self.partitions = len(self.parts)
            if self.async_dispatch:
                self._attach_dispatcher(idx, part)
            retired = topo.retired
            if idx in retired:
                retired = retired - {idx}
            self.topology = topo.evolve(partitions=self.partitions,
                                        retired=retired)
            return idx

    def restart_partition(self, index: int) -> dict:
        """FAILOVER: rebuild a (dead) partition from its WAL segment —
        RVs, adopted slices, and the shared allocator's high-water mark
        all survive; clients ride their cursors through the gap (the
        restarted partition's streams resume; at worst THAT partition
        relists, never its siblings)."""
        import os

        from kubernetes_tpu.apiserver.wal import attach_wal, restore_store

        with self._reshard_lock:
            if self._wal_dir is None:
                raise RuntimeError(
                    "partition failover requires an attached WAL")
            seg = os.path.join(self._wal_dir, f"p{index}")
            if index < len(self._wals):
                with contextlib.suppress(Exception):
                    self._wals[index].close()
            fresh = self._store_factory(rv_source=self._rv_seq.next)
            restore_store(seg, fresh)
            self._rv_seq.advance_to(fresh.current_rv())
            restored = sum(
                len(getattr(fresh, attr))
                for attr, _ in ClusterStore._KIND_TABLES.values())
            if index < len(self._wals):
                self._wals[index] = attach_wal(fresh, seg,
                                               **self._wal_kwargs)
            with self._subs_lock:
                for rec in self._sync_watches:
                    with contextlib.suppress(Exception):
                        rec["handles"][index].stop()
                    rec["handles"][index] = fresh.watch(
                        rec["fn"], rec["batch_fn"])
            if self.async_dispatch and index < len(self._part_handles):
                disp = self._dispatchers[index]
                with contextlib.suppress(Exception):
                    self._part_handles[index].stop()
                self._part_handles[index] = fresh.watch(
                    lambda e, d=disp: d.submit([e]),
                    batch_fn=lambda evs, d=disp: d.submit(list(evs)))
            if self._watch_caches is not None:
                from kubernetes_tpu.apiserver.watchcache import WatchCache

                self._watch_caches[index].stop()
                self._watch_caches[index] = WatchCache(fresh)
            self.parts[index] = fresh
            if self.topology is not None:
                # epoch bump: observers must re-validate against the
                # restarted partition (its watch history is gone)
                self.topology = self.topology.evolve()
            report = {"reason": "failover", "partition": index,
                      "restored_objects": restored,
                      "epoch": self.topology.epoch
                      if self.topology else None}
            self.migrations.append(report)
            return report

    def reshard_stats(self) -> dict:
        """The rebalancer's decision feed: per-partition object and
        mutation totals plus the per-slot / per-namespace write ledgers
        (mirrored into the PR 8 federation by the caller)."""
        parts = []
        for i, p in enumerate(self.parts):
            with p._lock:
                objs = sum(len(getattr(p, attr))
                           for attr, _ in p._KIND_TABLES.values())
                muts = sum(p._kind_seq.values())
            parts.append({"partition": i, "objects": objs,
                          "mutations": muts,
                          "retired": self.topology is not None
                          and i in self.topology.retired})
        return {
            "epoch": self.topology.epoch
            if self.topology is not None else 0,
            "partitions": parts,
            "slot_writes": dict(self.slot_writes),
            "ns_writes": dict(self.ns_writes),
            "frozen": sorted(self._frozen),
            "migrations": len(self.migrations),
        }
