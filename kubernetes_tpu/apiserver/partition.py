"""Partitioned control plane: the sharded store/watch fabric.

The single ``ClusterStore`` is the 50k-node wall: every byte of cluster
state flows through ONE lock, one watch fan-out, and (over REST) one
server process. Pathways (arXiv:2203.12533) makes the argument in the
large — past a point, throughput is won not by a faster single
coordinator but by sharding coordination across workers that proceed
asynchronously. This module applies that move to the control plane:

- ``partition_for`` — the ONE routing function (crc32, cross-process
  stable): objects shard by ``(kind, namespace-hash)`` for namespaced
  high-volume kinds (Pod) and by ``(kind, name-hash)`` for cluster-
  scoped high-volume kinds (Node); every other kind lives in partition
  0 so the long-tail API surface needs no fan-out.
- ``PartitionedStore`` — N independent ``ClusterStore`` partitions,
  each with its own lock, WAL segment (``attach_wal``), per-partition
  ``kind_seq`` sequence and latest-committed resourceVersion, behind a
  thin router that preserves today's store API exactly. RVs are
  allocated from ONE shared atomic counter so they stay globally
  unique/comparable; each partition's ``current_rv`` is the newest
  revision IT committed — the per-partition component of the composite
  cursor.
- ``CompositeCursor`` — the per-partition RV vector a list is
  consistent at. List+watch resume is per partition: a watch resumed
  from cursor component p misses nothing partition p committed after
  the list, and a torn stream on one partition relists ONLY that
  partition.
- per-partition **watch dispatch threads** (``async_dispatch=True``):
  a slow/stalled watcher callback on partition A can never delay
  delivery on partition B. Synchronous dispatch (the default) keeps
  ``partitions=1`` behaviorally identical to a bare ``ClusterStore``
  — the differential guard in tests/test_partition.py holds the two
  to identical event sequences, RVs and kind_seq values.
- ``capacity_guard=True`` — the multi-replica scheduler's bind-time
  arbiter: the router (which sees every bind, whichever partition the
  pod lives in) keeps a node-capacity ledger and rejects a bind that
  would oversubscribe a node with ``CapacityConflictError``. The
  losing replica's commit path unreserves/forgets/requeues through
  the PR 3 stale-commit machinery, so two scheduler brains can commit
  concurrently without double-binding a node.

Over REST the same routing function drives the *partition-aware
client* (``client/restcluster.py``): one apiserver process per
partition (each its own GIL — the sharded-coordinator deployment), one
watch stream per (kind, partition), bulk verbs split by partition and
fanned out.
"""

from __future__ import annotations

import contextlib
import queue
import threading
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.apiserver.store import ClusterStore, Event

# High-volume kinds that spread across partitions. Namespaced kinds
# shard by (kind, namespace) — the issue key — so one namespace's
# objects stay colocated (list/watch scoped to a namespace touches ONE
# partition); cluster-scoped Node shards by name so heartbeat storms
# and node watch fan-out spread too. Everything else (services, RBAC,
# leases, CRDs, Events, ...) lives in partition 0: correctness for the
# long tail costs zero fan-out code.
SHARDED_NAMESPACED_KINDS = frozenset({"Pod"})
SHARDED_CLUSTER_KINDS = frozenset({"Node"})


def partition_for(kind: str, namespace: Optional[str], name: Optional[str],
                  partitions: int) -> int:
    """The routing function — crc32-based so every process (stores,
    servers, clients, creator children) computes the same shard."""
    if partitions <= 1:
        return 0
    if kind in SHARDED_NAMESPACED_KINDS:
        key = f"{kind}/{namespace or 'default'}"
    elif kind in SHARDED_CLUSTER_KINDS:
        key = f"{kind}/{name or ''}"
    else:
        return 0
    return zlib.crc32(key.encode()) % partitions


def partitions_for(kind: str, partitions: int,
                   namespace: Optional[str] = None) -> List[int]:
    """Which partitions can hold objects of ``kind`` (the list/watch
    fan-out set). A namespace-scoped query on a namespaced sharded kind
    touches exactly one partition."""
    if partitions <= 1:
        return [0]
    if kind in SHARDED_NAMESPACED_KINDS:
        if namespace is not None:
            return [partition_for(kind, namespace, None, partitions)]
        return list(range(partitions))
    if kind in SHARDED_CLUSTER_KINDS:
        return list(range(partitions))
    return [0]


class CapacityConflictError(ValueError):
    """A bind that would oversubscribe its target node — the
    multi-replica conflict verdict. Subclasses ValueError so every
    existing bind-failure path (positional ``bind_many`` errors, the
    REST 409 mapping, the scheduler's unreserve/forget/requeue unwind)
    handles it with no new plumbing; the scheduler additionally counts
    it into ``stale_binds_rejected_total{path=bind_conflict}``."""


class CompositeCursor:
    """Per-partition RV vector: the resourceVersion a partitioned list
    is consistent at. Encodes as ``"v0.v1.v2"``; a 1-partition cursor
    encodes as the bare integer so single-partition consumers see
    exactly today's RV strings."""

    __slots__ = ("rvs",)

    def __init__(self, rvs):
        self.rvs: Tuple[int, ...] = tuple(int(v) for v in rvs)

    def encode(self) -> str:
        return ".".join(str(v) for v in self.rvs)

    @classmethod
    def parse(cls, text: str) -> "CompositeCursor":
        return cls(int(p or 0) for p in str(text).split("."))

    def component(self, partition: int) -> int:
        return self.rvs[partition] if partition < len(self.rvs) else 0

    def covers(self, other: "CompositeCursor") -> bool:
        """True when every component is >= the other's — "this list is
        at least as fresh as that one" (resume-safety check)."""
        if len(self.rvs) != len(other.rvs):
            return False
        return all(a >= b for a, b in zip(self.rvs, other.rvs))

    def __eq__(self, other) -> bool:
        return isinstance(other, CompositeCursor) and self.rvs == other.rvs

    def __repr__(self) -> str:
        return f"CompositeCursor({self.encode()})"


class _SharedSeq:
    """The partitions' shared resourceVersion allocator: globally
    unique, monotone, and advanceable past WAL-restored revisions (a
    restored store must never re-issue an RV below what its segments
    already committed)."""

    __slots__ = ("_lock", "_v")

    def __init__(self, start: int = 0):
        self._lock = threading.Lock()
        self._v = int(start)

    def next(self) -> int:
        with self._lock:
            self._v += 1
            return self._v

    def advance_to(self, n: int) -> None:
        with self._lock:
            self._v = max(self._v, int(n))


class _PartitionHandle:
    """Composite watch handle: one underlying registration per
    partition (sync mode) or a subscriber-list entry (async mode)."""

    def __init__(self, stop_fn: Callable[[], None]):
        self._stop_fn = stop_fn

    def stop(self) -> None:
        self._stop_fn()


class _Dispatcher:
    """One partition's watch dispatch thread: events enqueue under the
    partition lock (cheap append + notify) and fan out to subscribers
    on THIS thread — a watcher that blocks here stalls only this
    partition's deliveries, never a sibling's."""

    def __init__(self, index: int, subscribers_fn):
        self.index = index
        self._subscribers_fn = subscribers_fn
        self._q: "queue.Queue[Optional[List[Event]]]" = queue.Queue()
        # pending batches counted under a condition (not an Event off
        # the queue's emptiness: submit() enqueues after any emptiness
        # check the worker could make, so drain() must wait on a
        # counter that is incremented BEFORE the put and decremented
        # only after delivery completed)
        self._cond = threading.Condition()
        self._pending = 0
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"partition-dispatch-{index}")
        self._thread.start()

    def submit(self, events: List[Event]) -> None:
        with self._cond:
            self._pending += 1
        self._q.put(events)

    def _run(self) -> None:
        while True:
            events = self._q.get()
            if events is None:
                return
            try:
                for fn, batch_fn in self._subscribers_fn():
                    try:
                        if batch_fn is not None:
                            batch_fn(events)
                        else:
                            for e in events:
                                fn(e)
                    except Exception:  # noqa: BLE001 — one bad watcher
                        # must not kill the partition's dispatch thread
                        pass
            finally:
                with self._cond:
                    self._pending -= 1
                    if self._pending == 0:
                        self._cond.notify_all()

    def drain(self, timeout: float = 10.0) -> bool:
        with self._cond:
            return self._cond.wait_for(
                lambda: self._pending == 0, timeout)

    def stop(self) -> None:
        self._q.put(None)
        self._thread.join(timeout=2.0)


class _BindLedger:
    """Node-capacity arbiter for concurrent scheduler replicas. The
    router sees EVERY bind (the pod's partition serializes same-pod
    races; this ledger serializes same-node capacity races across
    partitions): reserve-then-bind, release on store rejection, so two
    brains committing simultaneously cannot jointly exceed a node's
    allocatable. Tracks milli-CPU + memory, the two axes every bench
    workload requests."""

    def __init__(self):
        self._lock = threading.Lock()
        self._alloc: Dict[str, Tuple[int, int]] = {}
        self._used: Dict[str, List[int]] = {}
        self._pod_req: Dict[str, Tuple[str, int, int]] = {}

    @staticmethod
    def _pod_request(pod) -> Tuple[int, int]:
        milli = mem = 0
        for c in pod.spec.containers:
            req = c.resources.requests
            q = req.get("cpu")
            if q is not None:
                milli += int(q.milli_value())
            q = req.get("memory")
            if q is not None:
                mem += int(q.value())
        return milli, mem

    def note_node(self, node) -> None:
        alloc = node.status.allocatable or node.status.capacity or {}
        cpu = alloc.get("cpu")
        mem = alloc.get("memory")
        with self._lock:
            self._alloc[node.name] = (
                int(cpu.milli_value()) if cpu is not None else 1 << 62,
                int(mem.value()) if mem is not None else 1 << 62,
            )

    def drop_node(self, name: str) -> None:
        with self._lock:
            self._alloc.pop(name, None)

    # reserve() verdicts: the caller must know whether THIS call
    # charged the ledger — a failed bind may only release its OWN
    # reservation, never a concurrent winner's (releasing on a same-pod
    # CAS loss would silently leak the winner's capacity)
    CONFLICT = 0
    CHARGED = 1
    KEPT = 2

    def reserve(self, key: str, pod, node_name: str) -> int:
        """Charge the pod against the node. ``CONFLICT`` = would
        oversubscribe (the bind must be refused); ``CHARGED`` = this
        call took the reservation (release it if the bind fails);
        ``KEPT`` = an earlier reservation (possibly a racing sibling's)
        already covers the pod — not this call's to release. Unknown
        nodes are not judged — the store deliberately accepts binds
        into the void (PR 3's guards own that failure mode)."""
        milli, mem = self._pod_request(pod)
        with self._lock:
            if key in self._pod_req:
                return self.KEPT
            alloc = self._alloc.get(node_name)
            if alloc is None:
                self._pod_req[key] = (node_name, milli, mem)
                return self.CHARGED
            used = self._used.setdefault(node_name, [0, 0])
            if used[0] + milli > alloc[0] or used[1] + mem > alloc[1]:
                return self.CONFLICT
            used[0] += milli
            used[1] += mem
            self._pod_req[key] = (node_name, milli, mem)
            return self.CHARGED

    def release(self, key: str, node_name: Optional[str] = None) -> None:
        """Drop the pod's reservation. With ``node_name`` given, only a
        reservation AGAINST THAT NODE is dropped — a losing bind must
        release exactly the charge it took, never one a racing sibling
        has since re-pointed to the node that actually won (confirm())."""
        with self._lock:
            got = self._pod_req.get(key)
            if got is None:
                return
            if node_name is not None and got[0] != node_name:
                return
            del self._pod_req[key]
            rec_node, milli, mem = got
            used = self._used.get(rec_node)
            if used is not None:
                used[0] -= milli
                used[1] -= mem

    def confirm(self, key: str, pod, node_name: str) -> None:
        """Align the ledger with a bind the store COMMITTED: whatever
        was reserved (possibly against a different node by a racing
        sibling whose target lost), the pod now occupies ``node_name``
        — charge it there unconditionally (committed truth outranks
        the budget; the guard's job was before the commit)."""
        milli, mem = self._pod_request(pod)
        with self._lock:
            got = self._pod_req.get(key)
            if got is not None:
                if got[0] == node_name:
                    return
                rec_node, r_milli, r_mem = got
                used = self._used.get(rec_node)
                if used is not None:
                    used[0] -= r_milli
                    used[1] -= r_mem
            used = self._used.setdefault(node_name, [0, 0])
            used[0] += milli
            used[1] += mem
            self._pod_req[key] = (node_name, milli, mem)


class PartitionedStore:
    """N independent store partitions behind today's ``ClusterStore``
    API. See the module docstring for the design; the router's job is
    purely mechanical — route single-object calls by ``partition_for``,
    fan list calls in, group bulk calls by partition, and keep the
    long tail (every non-sharded kind) on partition 0 so the untouched
    surface delegates via ``__getattr__``."""

    def __init__(self, partitions: int = 4, async_dispatch: bool = False,
                 capacity_guard: bool = False,
                 store_factory: Callable[..., ClusterStore] = ClusterStore):
        if partitions < 1:
            raise ValueError("partitions must be >= 1")
        self.partitions = int(partitions)
        self._rv_seq = _SharedSeq()
        self.parts: List[ClusterStore] = [
            store_factory(rv_source=self._rv_seq.next)
            for _ in range(self.partitions)
        ]
        self._subs_lock = threading.Lock()
        self._subs: List[Tuple[Callable, Optional[Callable]]] = []
        self.async_dispatch = bool(async_dispatch)
        self._dispatchers: List[_Dispatcher] = []
        self._part_handles: List = []
        if self.async_dispatch:
            for i, part in enumerate(self.parts):
                disp = _Dispatcher(i, self._subscribers)
                self._dispatchers.append(disp)
                self._part_handles.append(part.watch(
                    lambda e, d=disp: d.submit([e]),
                    batch_fn=lambda evs, d=disp: d.submit(list(evs)),
                ))
        self.ledger = _BindLedger() if capacity_guard else None
        self._wals: List[Any] = []
        self._watch_caches: Optional[List[Any]] = None

    # -- routing -------------------------------------------------------
    def _p(self, kind: str, namespace: Optional[str] = None,
           name: Optional[str] = None) -> ClusterStore:
        return self.parts[partition_for(kind, namespace, name,
                                        self.partitions)]

    def _fan(self, kind: str, namespace: Optional[str] = None
             ) -> List[ClusterStore]:
        return [self.parts[i]
                for i in partitions_for(kind, self.partitions, namespace)]

    def __getattr__(self, name: str):
        # the non-sharded long tail (services, RBAC, PV/PVC, CRDs,
        # leases, log/exec sources, ...) lives wholly in partition 0 —
        # its untouched ClusterStore surface IS the implementation
        if name.startswith("_") or name == "parts":
            raise AttributeError(name)
        return getattr(object.__getattribute__(self, "parts")[0], name)

    # event_ttl is a plain attribute on ClusterStore; writes must reach
    # partition 0 (where Events live), not shadow it on the router
    @property
    def event_ttl(self) -> float:
        return self.parts[0].event_ttl

    @event_ttl.setter
    def event_ttl(self, value: float) -> None:
        self.parts[0].event_ttl = value

    # -- watches -------------------------------------------------------
    def _subscribers(self) -> List[Tuple[Callable, Optional[Callable]]]:
        with self._subs_lock:
            return list(self._subs)

    def watch(self, fn: Callable[[Event], None],
              batch_fn: Optional[Callable[[List[Event]], None]] = None):
        if self.async_dispatch:
            entry = (fn, batch_fn)
            with self._subs_lock:
                self._subs.append(entry)

            def stop() -> None:
                with self._subs_lock:
                    if entry in self._subs:
                        self._subs.remove(entry)

            return _PartitionHandle(stop)
        handles = [p.watch(fn, batch_fn) for p in self.parts]
        return _PartitionHandle(lambda: [h.stop() for h in handles])

    def drain(self, timeout: float = 10.0) -> bool:
        """Block until every partition's dispatch queue is empty (async
        mode; tests and quiesce barriers)."""
        return all(d.drain(timeout) for d in self._dispatchers)

    def stop(self) -> None:
        for h in self._part_handles:
            h.stop()
        for d in self._dispatchers:
            d.stop()
        for wal in self._wals:
            with contextlib.suppress(Exception):
                wal.close()

    # -- resume (composite cursor) -------------------------------------
    def enable_resume(self, capacity: int = 100_000) -> None:
        """Attach one revisioned watch cache per partition — the
        replay half of list+watch resume (``watch_from_cursor``)."""
        if self._watch_caches is None:
            from kubernetes_tpu.apiserver.watchcache import WatchCache

            self._watch_caches = [WatchCache(p, capacity=capacity)
                                  for p in self.parts]

    def cursor(self) -> CompositeCursor:
        """The store's current composite cursor (one component per
        partition: the newest revision that partition committed)."""
        return CompositeCursor(p.current_rv() for p in self.parts)

    def list_with_cursor(self, kind: str,
                         namespace: Optional[str] = None
                         ) -> Tuple[List[Any], CompositeCursor]:
        """List + the composite cursor the list is consistent at: a
        per-partition watch resumed from component p misses nothing
        partition p committed after its slice of this list."""
        objs: List[Any] = []
        rvs = [p.current_rv() for p in self.parts]
        for i in partitions_for(kind, self.partitions, namespace):
            got, rv = self.parts[i].list_objects_with_rv(kind, namespace)
            objs.extend(got)
            rvs[i] = rv
        return objs, CompositeCursor(rvs)

    def watch_from_cursor(self, cursor: CompositeCursor,
                          fn: Callable[[int, Event], None]):
        """Resume watching from a composite cursor: per partition,
        replay everything committed after the cursor component, then
        stream live (``enable_resume`` must have been called before the
        cursor was taken). A component that has been compacted out
        raises ``TooOldResourceVersion`` — the caller relists THAT
        partition only."""
        if self._watch_caches is None:
            raise RuntimeError("enable_resume() was never called")
        handles = []
        try:
            for i, cache in enumerate(self._watch_caches):
                handles.append(cache.watch_from(cursor.component(i), fn))
        except Exception:
            for h in handles:
                h.stop()
            raise
        return _PartitionHandle(lambda: [h.stop() for h in handles])

    # -- durability ----------------------------------------------------
    def attach_wal(self, wal_dir: str, restore: bool = False,
                   **kwargs) -> List[Any]:
        """One WAL segment per partition (``<dir>/p<k>/wal.jsonl``):
        partitions serialize their own mutations, so segments append
        with zero cross-partition contention and restore in any order.
        ``restore=True`` first replays each partition's snapshot+log
        (crash recovery) and advances the shared RV allocator past
        every restored revision — a recovered store must never re-issue
        a committed RV."""
        import os

        from kubernetes_tpu.apiserver.wal import attach_wal, restore_store

        for i, part in enumerate(self.parts):
            seg = os.path.join(wal_dir, f"p{i}")
            os.makedirs(seg, exist_ok=True)
            if restore:
                restore_store(seg, part)
            self._wals.append(attach_wal(part, seg, **kwargs))
        self._rv_seq.advance_to(max(p.current_rv() for p in self.parts))
        return list(self._wals)

    # -- observability -------------------------------------------------
    def partition_registries(self):
        """One tiny metrics registry per partition (scraped by the
        scale harness through the PR 8 federation as
        ``instance=partition-<k>``): latest committed RV, object
        count, and cumulative kind_seq mutations."""
        from kubernetes_tpu.metrics.registry import Gauge, MetricsRegistry

        out = []
        for i, part in enumerate(self.parts):
            reg = MetricsRegistry()
            rv = Gauge("partition_resource_version",
                       "Newest revision this partition committed")
            objs = Gauge("partition_objects",
                         "Objects resident in this partition")
            muts = Gauge("partition_mutations_total",
                         "Cumulative per-kind mutation count")
            reg.register(rv)
            reg.register(objs)
            reg.register(muts)
            rv.set(float(part.current_rv()))
            with part._lock:
                objs.set(float(sum(
                    len(getattr(part, attr))
                    for attr, _ in part._KIND_TABLES.values())))
                muts.set(float(sum(part._kind_seq.values())))
            out.append(reg)
        return out

    # -- pods ----------------------------------------------------------
    def create_pod(self, pod):
        created = self._p("Pod", pod.namespace).create_pod(pod)
        if self.ledger is not None and pod.spec.node_name:
            self.ledger.reserve(pod.full_name(), pod, pod.spec.node_name)
        return created

    def create_pods(self, pods):
        by_part: Dict[ClusterStore, list] = {}
        for pod in pods:
            by_part.setdefault(self._p("Pod", pod.namespace),
                               []).append(pod)
        for part, group in by_part.items():
            part.create_pods(group)
        if self.ledger is not None:
            for pod in pods:
                if pod.spec.node_name:
                    self.ledger.reserve(pod.full_name(), pod,
                                        pod.spec.node_name)
        return pods

    def bind(self, namespace: str, name: str, uid: str,
             node_name: str) -> None:
        part = self._p("Pod", namespace)
        key = f"{namespace}/{name}"
        charged = False
        pod = None
        if self.ledger is not None:
            pod = part.get_pod(namespace, name)
            if pod is not None and not pod.spec.node_name:
                verdict = self.ledger.reserve(key, pod, node_name)
                if verdict == _BindLedger.CONFLICT:
                    raise CapacityConflictError(
                        f"pod {key}: capacity conflict on node "
                        f"{node_name!r} (concurrent replica won the "
                        f"remaining capacity)")
                charged = verdict == _BindLedger.CHARGED
        try:
            part.bind(namespace, name, uid, node_name)
        except Exception:
            # release ONLY the reservation this call took (keyed to its
            # own node): on a same-pod CAS loss the surviving charge —
            # possibly already re-pointed by the winner's confirm —
            # belongs to the winner
            if charged:
                self.ledger.release(key, node_name)
            raise
        if self.ledger is not None and pod is not None:
            # the store committed THIS node: align the ledger even when
            # a racing sibling reserved the pod against a different
            # target first (committed truth outranks the reservation)
            self.ledger.confirm(key, pod, node_name)

    def bind_many(self, bindings):
        errors: List[Optional[Exception]] = [None] * len(bindings)
        by_part: Dict[ClusterStore, list] = {}
        for i, b in enumerate(bindings):
            namespace, name, uid, node_name = b
            charged = False
            pod = None
            if self.ledger is not None:
                key = f"{namespace}/{name}"
                part = self._p("Pod", namespace)
                pod = part.get_pod(namespace, name)
                if pod is not None and not pod.spec.node_name:
                    verdict = self.ledger.reserve(key, pod, node_name)
                    if verdict == _BindLedger.CONFLICT:
                        errors[i] = CapacityConflictError(
                            f"pod {key}: capacity conflict on node "
                            f"{node_name!r} (concurrent replica won "
                            f"the remaining capacity)")
                        continue
                    charged = verdict == _BindLedger.CHARGED
            by_part.setdefault(self._p("Pod", namespace),
                               []).append((i, b, charged, pod))
        for part, group in by_part.items():
            got = part.bind_many([b for _, b, _, _ in group])
            for (i, b, charged, pod), err in zip(group, got):
                errors[i] = err
                if self.ledger is None:
                    continue
                key = f"{b[0]}/{b[1]}"
                if err is not None:
                    # as in bind(): only this call's own reservation,
                    # keyed to its own node
                    if charged:
                        self.ledger.release(key, b[3])
                elif pod is not None:
                    self.ledger.confirm(key, pod, b[3])
        return errors

    def update_pod(self, pod):
        return self._p("Pod", pod.namespace).update_pod(pod)

    def delete_pod(self, namespace: str, name: str) -> None:
        if self.ledger is not None:
            self.ledger.release(f"{namespace}/{name}")
        self._p("Pod", namespace).delete_pod(namespace, name)

    def delete_pods(self, keys) -> None:
        by_part: Dict[ClusterStore, list] = {}
        for namespace, name in keys:
            if self.ledger is not None:
                self.ledger.release(f"{namespace}/{name}")
            by_part.setdefault(self._p("Pod", namespace),
                               []).append((namespace, name))
        for part, group in by_part.items():
            part.delete_pods(group)

    def get_pod(self, namespace: str, name: str):
        return self._p("Pod", namespace).get_pod(namespace, name)

    def list_pods(self, namespace: Optional[str] = None):
        out: List[Any] = []
        for part in self._fan("Pod", namespace):
            out.extend(part.list_pods(namespace))
        return out

    def patch_pod_condition(self, namespace: str, name: str,
                            condition) -> None:
        self._p("Pod", namespace).patch_pod_condition(namespace, name,
                                                      condition)

    def set_nominated_node_name(self, namespace: str, name: str,
                                node: str) -> None:
        self._p("Pod", namespace).set_nominated_node_name(namespace,
                                                          name, node)

    def clear_nominated_node_name(self, namespace: str, name: str) -> None:
        self._p("Pod", namespace).clear_nominated_node_name(namespace,
                                                            name)

    def set_pod_phase(self, namespace: str, name: str, phase: str,
                      pod_ip: str = "", host_ip: str = "") -> bool:
        return self._p("Pod", namespace).set_pod_phase(
            namespace, name, phase, pod_ip, host_ip)

    def batched_status_writes(self):
        return contextlib.nullcontext()

    # -- nodes ---------------------------------------------------------
    def add_node(self, node) -> None:
        if self.ledger is not None:
            self.ledger.note_node(node)
        self._p("Node", None, node.name).add_node(node)

    def update_node(self, node) -> None:
        if self.ledger is not None:
            self.ledger.note_node(node)
        self._p("Node", None, node.name).update_node(node)

    def delete_node(self, name: str) -> None:
        if self.ledger is not None:
            self.ledger.drop_node(name)
        self._p("Node", None, name).delete_node(name)

    def get_node(self, name: str):
        return self._p("Node", None, name).get_node(name)

    def list_nodes(self):
        out: List[Any] = []
        for part in self._fan("Node"):
            out.extend(part.list_nodes())
        return out

    # -- generic typed-object surface ----------------------------------
    def kind_seq(self, kind: str) -> int:
        return sum(p.kind_seq(kind)
                   for p in self._fan(kind))

    def current_rv(self) -> int:
        return max(p.current_rv() for p in self.parts)

    def known_kinds(self):
        return self.parts[0].known_kinds()

    def kind_is_namespaced(self, kind: str) -> bool:
        return self.parts[0].kind_is_namespaced(kind)

    def create_object(self, kind: str, obj):
        if self.ledger is not None and kind == "Node":
            self.ledger.note_node(obj)
        return self._p(kind, obj.metadata.namespace,
                       obj.metadata.name).create_object(kind, obj)

    def create_objects_bulk(self, kind: str, objs) -> int:
        if self.ledger is not None and kind == "Node":
            for obj in objs:
                self.ledger.note_node(obj)
        by_part: Dict[ClusterStore, list] = {}
        for obj in objs:
            by_part.setdefault(
                self._p(kind, obj.metadata.namespace, obj.metadata.name),
                []).append(obj)
        return sum(part.create_objects_bulk(kind, group)
                   for part, group in by_part.items())

    def update_object(self, kind: str, obj, expect_rv=None):
        return self._p(kind, obj.metadata.namespace,
                       obj.metadata.name).update_object(
                           kind, obj, expect_rv=expect_rv)

    def delete_object(self, kind: str, namespace: str, name: str) -> bool:
        return self._p(kind, namespace, name).delete_object(
            kind, namespace, name)

    def get_object(self, kind: str, namespace: str, name: str):
        return self._p(kind, namespace, name).get_object(
            kind, namespace, name)

    def mutate_object(self, kind: str, namespace: str, name: str,
                      mutate, retries: int = 8):
        return self._p(kind, namespace, name).mutate_object(
            kind, namespace, name, mutate, retries=retries)

    def add_finalizer(self, kind: str, namespace: str, name: str,
                      finalizer: str) -> bool:
        return self._p(kind, namespace, name).add_finalizer(
            kind, namespace, name, finalizer)

    def remove_finalizer(self, kind: str, namespace: str, name: str,
                         finalizer: str) -> bool:
        return self._p(kind, namespace, name).remove_finalizer(
            kind, namespace, name, finalizer)

    def list_objects(self, kind: str,
                     namespace: Optional[str] = None):
        return self.list_objects_with_rv(kind, namespace)[0]

    def list_objects_with_rv(self, kind: str,
                             namespace: Optional[str] = None):
        objs: List[Any] = []
        rv = 0
        for part in self._fan(kind, namespace):
            got, part_rv = part.list_objects_with_rv(kind, namespace)
            objs.extend(got)
            rv = max(rv, part_rv)
        return objs, rv
