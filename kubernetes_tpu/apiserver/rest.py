"""HTTP REST API server + remote client.

The process-boundary surface of the framework — the behavioral equivalent
of kube-apiserver's endpoint layer (reference
``staging/src/k8s.io/apiserver/pkg/endpoints/handlers/{create,get,update,
delete,watch}.go`` + ``pkg/controlplane/instance.go:547 InstallLegacyAPI``):

- handler chain per request: flow-control admission (API Priority &
  Fairness, ``apiserver/flowcontrol.py`` — FlowSchemas route identities
  to priority levels with shuffle-sharded fair queues and seat/width
  accounting; the legacy readonly/mutating max-in-flight lanes remain
  behind ``flow_control=None``) → authenticate → authorize → (mutating
  requests) admission → registry operation against the cluster store
- resource routes ``/api/v1/<plural>``, ``/api/v1/namespaces/{ns}/<plural>``,
  object routes ``.../{name}``, subresources ``.../pods/{name}/binding``
  (reference ``pkg/registry/core/pod/storage/storage.go:159``) and
  ``.../pods/{name}/status``
- watches: ``GET ...?watch=true&resourceVersion=N`` streams chunked
  frames, replaying from N via the revisioned watch cache — the same
  List+Watch contract client-go reflectors consume. A compacted N returns
  HTTP 410 Gone ("Expired"), telling the client to relist. Delivery is
  PIPELINED: events are coalesced per chunk (binary clients get one
  length-prefixed frame carrying a batch of per-event pickles, cached so
  N watchers never pay N encodes; JSON clients get several newline-
  delimited ``{"type": ..., "object": {...}}`` documents per chunk), with
  a small flush window so informer catch-up on 30k pods costs
  O(batches) syscalls, not O(pods).
- bulk hot-path verbs: POST ``{Kind}List`` to a collection,
  POST ``/api/v1/bindings`` (BindingList), and POST ``/api/v1/statuses``
  (PodStatusList) apply N objects per request with positional failures —
  per-object semantics, per-batch wire cost.
- ``/healthz`` ``/livez`` ``/readyz`` probes and Prometheus ``/metrics``
  — all exempt from flow control (a liveness probe must never be queued
  or 429'd), like the ``/debug/*`` admin routes, which include
  ``/debug/apf`` (flow-control introspection) and ``/debug/slo``
  (live SLO evaluation over the cluster SLIs)

Transport negotiates per request between JSON over HTTP/1.1 chunked
streams (the kubectl/debug wire, ``kubernetes_tpu.api.serialization``)
and the binary codec (``kubernetes_tpu.apiserver.codec`` — the analog of
the reference's ``application/vnd.kubernetes.protobuf``), which control-
plane clients use for every hot-path payload. Per-request overhead is
amortized server-side too: selector-free binary list responses are
served from a per-kind pre-encoded cache, and authn/authz resolution
sits behind token→identity / decision LRUs invalidated by the relevant
object events.
"""

from __future__ import annotations

import json
import queue
import socket
import struct
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from kubernetes_tpu.api.serialization import SCHEME, from_wire, is_namespaced, to_wire
from kubernetes_tpu.apiserver.faults import FaultGate, resource_of
from kubernetes_tpu.apiserver.flowcontrol import (
    FlowControlConfig,
    FlowController,
    LaneStats,
    Rejected,
    default_config,
    namespace_of,
)
from kubernetes_tpu.apiserver.admission import (
    CREATE,
    DELETE,
    UPDATE,
    AdmissionChain,
    AdmissionError,
    AdmissionRequest,
)
from kubernetes_tpu.apiserver.store import (
    ClusterStore,
    ConflictError,
    Event,
    ValidationError,
)
from kubernetes_tpu.apiserver.watchcache import TooOldResourceVersion, WatchCache

# plural route segment ↔ kind
PLURALS: Dict[str, str] = {
    "pods": "Pod",
    "nodes": "Node",
    "services": "Service",
    "endpoints": "Endpoints",
    "replicasets": "ReplicaSet",
    "replicationcontrollers": "ReplicationController",
    "statefulsets": "StatefulSet",
    "deployments": "Deployment",
    "daemonsets": "DaemonSet",
    "jobs": "Job",
    "persistentvolumeclaims": "PersistentVolumeClaim",
    "persistentvolumes": "PersistentVolume",
    "storageclasses": "StorageClass",
    "csinodes": "CSINode",
    "poddisruptionbudgets": "PodDisruptionBudget",
    "events": "Event",
    "namespaces": "Namespace",
    "resourcequotas": "ResourceQuota",
    "serviceaccounts": "ServiceAccount",
    "cronjobs": "CronJob",
    "horizontalpodautoscalers": "HorizontalPodAutoscaler",
    "endpointslices": "EndpointSlice",
    "roles": "Role",
    "clusterroles": "ClusterRole",
    "rolebindings": "RoleBinding",
    "clusterrolebindings": "ClusterRoleBinding",
    "customresourcedefinitions": "CustomResourceDefinition",
    "mutatingwebhookconfigurations": "MutatingWebhookConfiguration",
    "validatingwebhookconfigurations": "ValidatingWebhookConfiguration",
    "secrets": "Secret",
    "configmaps": "ConfigMap",
    "certificatesigningrequests": "CertificateSigningRequest",
    "priorityclasses": "PriorityClass",
    "leases": "Lease",
}
KIND_TO_PLURAL = {k: p for p, k in PLURALS.items()}


class Forbidden(Exception):
    pass


def _encode_custom(obj, api_version: str) -> Dict:
    """CustomObject → wire at a served version: None-conversion (the
    apiextensions default) rewrites only the apiVersion stamp."""
    d = to_wire(obj)
    d["apiVersion"] = api_version
    return d


def _cached_event_bytes(event: Event, version: int = 2) -> bytes:
    """Pickle one watch event as ``(type, obj, old, commit_ts)``,
    memoized on the event so N binary watchers (and the replay path)
    pay ONE encode — the reference's cachingObject, applied to the
    binary wire. The commit timestamp rides along so the client can
    measure end-to-end watch delivery (freshness SLI); decoders accept
    the legacy 3-tuple too. A watcher pinned to codec v1 (mixed-version
    roll: codec.negotiate) gets the legacy 3-tuple from its own memo
    slot — the wire contract is the negotiated one, not whatever the
    server happens to emit. Benign race: two watch writers may both
    encode the first time; both produce identical bytes and one
    assignment wins."""
    from kubernetes_tpu.apiserver import codec

    if version < 2:
        b = event.__dict__.get("_bin_frame_v1")
        if b is None:
            b = codec.encode((event.type, event.obj, event.old_obj))
            event.__dict__["_bin_frame_v1"] = b
        return b
    b = event.__dict__.get("_bin_frame")
    if b is None:
        # the commit-time origin trace context (fleet tracing) rides
        # INSIDE the ts slot — ``(ts, origin)`` instead of a bare float
        # — so the 4-tuple wire contract is unchanged for untraced
        # events and v2 decoders distinguish the shapes by type
        ts = event.ts
        origin = getattr(event, "origin", None)
        if origin is not None:
            ts = (ts, origin)
        b = codec.encode(
            (event.type, event.obj, event.old_obj, ts))
        event.__dict__["_bin_frame"] = b
    return b


def resources_metrics_text(store: ClusterStore) -> str:
    """The /metrics/resources exposition (reference
    ``pkg/scheduler/metrics/resources/resources.go`` podResourceCollector):
    kube_pod_resource_request / kube_pod_resource_limit gauges with
    {namespace, pod, node, resource, unit} labels, aggregated with the
    scheduler's own request math (max(sum(containers), init) + overhead)
    so operators see demand exactly as scheduling sees it."""
    from kubernetes_tpu.scheduler.types import compute_pod_resource_request

    unit_of = {"cpu": "cores", "memory": "bytes",
               "ephemeral-storage": "bytes"}
    lines = [
        "# HELP kube_pod_resource_request Resources requested by workloads "
        "on the cluster, broken down by pod.",
        "# TYPE kube_pod_resource_request gauge",
    ]
    limits_lines = [
        "# HELP kube_pod_resource_limit Resources limit for workloads on "
        "the cluster, broken down by pod.",
        "# TYPE kube_pod_resource_limit gauge",
    ]

    def fmt(value) -> str:
        # full precision: {:g} truncates to 6 significant digits, which
        # corrupts byte-valued gauges (16Gi would round off by ~31KB)
        if float(value) == int(value):
            return str(int(value))
        return repr(float(value))

    def emit(out, metric, pod, resource, value):
        unit = unit_of.get(resource, "integer")
        out.append(
            f'{metric}{{namespace="{pod.namespace}",pod="{pod.name}",'
            f'node="{pod.spec.node_name}",resource="{resource}",'
            f'unit="{unit}"}} {fmt(value)}'
        )

    def pod_limits(pod):
        """Aggregate limits with the same shape as requests:
        max(sum(app containers), max(init containers)) + overhead per
        resource (the reference podResourceCollector adds spec.overhead
        to limits as well as requests)."""
        total: Dict[str, float] = {}
        for c in pod.spec.containers:
            for name, qty in c.resources.limits.items():
                v = qty.milli_value() / 1000.0 if name == "cpu" \
                    else qty.value()
                total[name] = total.get(name, 0) + v
        for c in pod.spec.init_containers:
            for name, qty in c.resources.limits.items():
                v = qty.milli_value() / 1000.0 if name == "cpu" \
                    else qty.value()
                total[name] = max(total.get(name, 0), v)
        for name, qty in (pod.spec.overhead or {}).items():
            # overhead extends NON-ZERO limits only (reference PodLimits
            # guards with `found && !value.IsZero()`)
            if total.get(name):
                v = qty.milli_value() / 1000.0 if name == "cpu" \
                    else qty.value()
                total[name] += v
        return total

    for pod in store.list_pods():
        req = compute_pod_resource_request(pod)
        if req.milli_cpu:
            emit(lines, "kube_pod_resource_request", pod, "cpu",
                 req.milli_cpu / 1000.0)
        if req.memory:
            emit(lines, "kube_pod_resource_request", pod, "memory",
                 req.memory)
        if req.ephemeral_storage:
            emit(lines, "kube_pod_resource_request", pod,
                 "ephemeral-storage", req.ephemeral_storage)
        for name, v in req.scalar_resources.items():
            emit(lines, "kube_pod_resource_request", pod, name, v)
        for name, v in pod_limits(pod).items():
            emit(limits_lines, "kube_pod_resource_limit", pod, name, v)
    return "\n".join(lines + limits_lines) + "\n"


# per-kind selectable fields (reference ToSelectableFields:
# pkg/registry/core/pod/strategy.go, node/strategy.go; every other kind
# supports only the generic metadata pair) — an unlisted field is the
# client's 400 regardless of whether any object exists to filter
_GENERIC_FIELDS = {"metadata.name", "metadata.namespace"}
_SELECTABLE_FIELDS = {
    "Pod": _GENERIC_FIELDS | {
        "spec.nodeName", "spec.restartPolicy", "spec.schedulerName",
        "spec.serviceAccountName", "status.phase", "status.podIP",
        "status.nominatedNodeName",
    },
    "Node": _GENERIC_FIELDS | {"spec.unschedulable"},
    "Event": _GENERIC_FIELDS | {
        "involvedObject.kind", "involvedObject.name", "reason", "type",
    },
}


def _parse_field_selector(kind: str, expr: str) -> List[tuple]:
    """Parse + VALIDATE a field selector ("k=v,k2!=v2") against the
    kind's selectable-field set. Validation is unconditional — upstream
    rejects unsupported selectors even when nothing would be filtered."""
    allowed = _SELECTABLE_FIELDS.get(kind, _GENERIC_FIELDS)
    checks: List[tuple] = []
    for part in expr.split(","):
        part = part.strip()
        if not part:
            continue
        if "!=" in part:
            key, _, val = part.partition("!=")
            want_eq = False
        elif "==" in part:
            key, _, val = part.partition("==")
            want_eq = True
        elif "=" in part:
            key, _, val = part.partition("=")
            want_eq = True
        else:
            raise ValueError(f"invalid field selector clause {part!r}")
        key = key.strip()
        if key not in allowed:
            raise ValueError(f"field label not supported: {key!r}")
        checks.append((key, val.strip(), want_eq))
    return checks


def _field_checks_match(obj, checks: List[tuple]) -> bool:
    import re

    def resolve(path: str) -> str:
        cur = obj
        for seg in path.split("."):
            # collapse acronym runs: podIP -> pod_ip, not pod_i_p
            snake = re.sub(r"(?<=[a-z0-9])(?=[A-Z])", "_", seg).lower()
            cur = getattr(cur, snake, "")
        if cur is None:
            return ""
        if isinstance(cur, bool):
            return "true" if cur else "false"   # wire casing
        return str(cur)

    return all((resolve(key) == val) == want_eq
               for key, val, want_eq in checks)


def json_merge_patch(target: Any, patch: Any) -> Any:
    """RFC 7386 JSON Merge Patch: nulls delete keys, objects merge
    recursively, everything else replaces."""
    if not isinstance(patch, dict):
        return patch
    out = dict(target) if isinstance(target, dict) else {}
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        elif isinstance(v, dict):
            out[k] = json_merge_patch(out.get(k), v)
        else:
            out[k] = v
    return out


Authorizer = Callable[[str, str, str, str], bool]  # (user, verb, kind, ns)


def allow_all(user: str, verb: str, kind: str, namespace: str) -> bool:
    return True


class _DevNullWriter:
    """Stands in for wfile after a fault aborted the connection, so the
    base handler's post-request flush/close never touches the dead
    socket (which would traceback on every injected reset)."""

    closed = False

    def write(self, data) -> int:
        return len(data)

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class _TruncatingWriter:
    """Passes through the first ``limit`` bytes, then RSTs the
    connection and swallows the rest — the 'response cut mid-body'
    failure mode (a proxy died, a socket buffer was torn down)."""

    closed = False

    def __init__(self, handler: "_Handler", inner, limit: int):
        self._handler = handler
        self._inner = inner
        self._remaining = max(0, int(limit))
        self._aborted = False

    def write(self, data) -> int:
        if self._aborted:
            return len(data)
        take = data[:self._remaining]
        if take:
            try:
                self._inner.write(take)
            except OSError:
                self._aborted = True
                return len(data)
            self._remaining -= len(take)
        if self._remaining <= 0:
            try:
                self._inner.flush()
            except OSError:
                pass
            self._aborted = True
            self._handler._abort_socket()
        return len(data)

    def flush(self) -> None:
        if not self._aborted:
            try:
                self._inner.flush()
            except OSError:
                pass

    def close(self) -> None:
        pass

    def finish_request(self) -> None:
        """The faulted request is over: a truncation fault always ends
        the connection — even when the response fit under the byte
        limit — so the writer never leaks into the next keep-alive
        request with leftover budget."""
        if not self._aborted:
            try:
                self._inner.flush()
            except OSError:
                pass
            self._aborted = True
            self._handler._abort_socket()


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    # a request/response ping-pong on a keep-alive connection stalls
    # ~40ms per round trip under Nagle + delayed ACK; the reference
    # apiserver's HTTP/2 stack never batches this way either
    disable_nagle_algorithm = True
    server: "APIServer"

    # -- plumbing ------------------------------------------------------
    def log_message(self, fmt, *args):  # quiet
        pass

    # -- admin-route registry: the debug control surfaces, declared once
    # so every cross-cutting policy (FaultGate exemption, lane-slot
    # exemption, verb dispatch) derives from the same table instead of
    # hand-rolled path checks per verb handler. All admin routes share
    # the control-plane trust envelope and must stay reachable while the
    # server is sick — chaos must not lock out its own controls, and a
    # full lane must not block the postmortem dump.
    ADMIN_ROUTES = {
        "/debug/faults": "_serve_faults_admin",
        "/debug/trace": "_serve_trace_admin",
        "/debug/apf": "_serve_apf_admin",
        "/debug/slo": "_serve_slo_admin",
        "/debug/partition": "_serve_partition_admin",
    }

    # -- flow-control exemption envelope: paths that must NEVER be
    # queued, rejected, or charged seats — by either admission path.
    # Flow control must never fail a liveness probe (429 under load
    # would get the server restarted exactly when it's busy), never
    # blind the metrics scraper, and (via ADMIN_ROUTES) never lock out
    # the debug surfaces mid-overload.
    _EXEMPT_PATHS = ("/healthz", "/livez", "/readyz",
                     "/metrics", "/metrics/resources",
                     "/api/v1/partitiontopology",
                     "/api/v1/subscription")

    def _admission_exempt(self, path: str) -> bool:
        return path in self.ADMIN_ROUTES or path in self._EXEMPT_PATHS

    # -- legacy max-in-flight gate (reference apiserver filters/
    # maxinflight.go: separate readonly and mutating lanes; a full lane
    # answers 429 with a COMPUTED Retry-After so one hot client cannot
    # starve the control plane). Active only when the server was built
    # with ``flow_control=None``; the APF path below replaces it
    # otherwise. Long-running requests (watches) are exempt, as
    # upstream's longRunningRequestCheck exempts them.
    def _gate(self) -> Optional[Tuple[threading.Semaphore, LaneStats]]:
        path = self.path.split("?", 1)[0]
        if self._admission_exempt(path):
            # admin surfaces never consume a lane slot: /debug/trace is
            # exactly for when the server is overloaded, and /debug/
            # faults must stay operable mid-chaos
            return None
        if self.command in ("GET", "HEAD"):
            if "watch=" in self.path:
                return None      # long-running: never counts against a lane
            if self.server.readonly_lane is None:
                return None
            return self.server.readonly_lane, self.server.lane_stats["ro"]
        if self.server.mutating_lane is None:
            return None
        return self.server.mutating_lane, self.server.lane_stats["rw"]

    # -- fault injection (faults.py FaultGate; the chaos-over-REST
    # middleware). Runs BEFORE the in-flight lanes so an injected reset
    # never consumes a lane slot; the exemption envelope is the SAME
    # set admission honors (plus ADMIN_ROUTES, checked at the call
    # sites) — a probe path added to one layer's exemption and not the
    # other would silently let chaos 429 a liveness probe that
    # admission promised never to fail.
    _FAULT_EXEMPT = _EXEMPT_PATHS

    _sock_aborted = False   # instance flag set by _abort_socket
    _apf_ticket = None      # live APF ticket while a request executes

    def _abort_socket(self) -> None:
        """RST the client (SO_LINGER 1,0 → no FIN, no more bytes) and
        neuter wfile so the base class's final flush is a no-op."""
        self._sock_aborted = True
        self.close_connection = True
        try:
            self.connection.setsockopt(
                socket.SOL_SOCKET, socket.SO_LINGER,
                struct.pack("ii", 1, 0))
        except OSError:
            pass
        try:
            self.connection.close()
        except OSError:
            pass
        self.wfile = _DevNullWriter()

    def _inject_fault(self) -> bool:
        """Consult the FaultGate for this request. True = the request
        was fully consumed by the fault (aborted or answered); False =
        continue normal handling (possibly slowed or truncated)."""
        gate = self.server.fault_gate
        if gate is None or not gate._rules:
            return False
        path = self.path.split("?", 1)[0]
        if path in self._FAULT_EXEMPT or path in self.ADMIN_ROUTES:
            return False
        rule = gate.decide(self.command, resource_of(self.path))
        if rule is None:
            return False
        if rule.fault == "latency":
            time.sleep(rule.latency)
            return False
        if rule.fault == "truncate":
            self.wfile = _TruncatingWriter(self, self.wfile,
                                           rule.truncate_bytes)
            return False
        if rule.fault == "reset":
            self._abort_socket()
            return True
        # "error": overload pushback burst — drain the body first so
        # keep-alive framing stays intact for the client's retry
        length = int(self.headers.get("Content-Length") or 0)
        if length:
            self.rfile.read(length)
        body = json.dumps({
            "kind": "Status", "status": "Failure",
            "reason": "TooManyRequests" if rule.code == 429
            else "ServiceUnavailable",
            "message": "injected fault: overload pushback",
            "code": rule.code,
        }).encode()
        self.send_response(rule.code)
        self.send_header("Retry-After", str(rule.retry_after))
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
        return True

    def _handle_gated(self, inner) -> None:
        self._body_consumed = False   # per-request: see _send_429 drain
        # pin the wire version FIRST: every response (including the
        # fault-injected and 429 paths below) carries the echoed stamp,
        # so a mid-roll client always learns what contract it got
        from kubernetes_tpu.apiserver import codec

        try:
            self._codec_version = codec.negotiate(
                self.headers.get(codec.VERSION_HEADER))
        except ValueError as e:
            # unsatisfiable stamp: explicit refusal, never a silent
            # decode skew. Drop keep-alive — the body framing of a
            # client this confused is not worth trusting.
            self.close_connection = True
            self._send_error(400, "UnsupportedCodecVersion", str(e))
            return
        if self._inject_fault():
            return
        from kubernetes_tpu.observability.tracer import (
            TRACE_HEADER, parse_trace_header, set_request_context)

        ctx = parse_trace_header(self.headers.get(TRACE_HEADER))
        if ctx is not None:
            self.server.trace_headers_seen += 1
        tracer = self.server.tracer
        span = None
        if tracer is not None and tracer.enabled \
                and "watch=" not in self.path:
            # watches are long-running: a span per watch would never
            # close while the stream lives (upstream's longRunning
            # exemption, applied to tracing too). A propagated context
            # carries the CLIENT's sampling decision and it wins both
            # ways: sampled=1 always opens the server-side child span
            # (bypassing the 1-in-N fallback — the sampled pod's trace
            # must stitch across every hop), sampled=0 never does.
            # Context-free requests keep the 1-in-N fallback — an
            # unsampled span per request would wrap the ring in seconds
            # at bench request rates and evict the sampled pod traces
            # the recorder exists to keep.
            if ctx is not None:
                if ctx.sampled:
                    # the wire parent span id is a DIFFERENT process's
                    # counter (span ids are per-process and collide
                    # across the fleet), so it rides as an attribute
                    # and the server span is a local root; the merged
                    # timeline stitches hops by trace id + ctx_parent.
                    span = tracer.span(
                        f"rest.{self.command}", trace=ctx.trace,
                        path=self.path.split("?", 1)[0],
                        ctx_parent=ctx.parent)
            else:
                rate = tracer.sample_rate
                if rate >= 1.0 or (rate > 0.0 and
                                   next(self.server._req_seq)
                                   % max(1, round(1.0 / rate)) == 0):
                    span = tracer.span(f"rest.{self.command}",
                                       path=self.path.split("?", 1)[0])
        set_request_context(ctx)
        try:
            if span is not None:
                with span:
                    self._dispatch_gated(inner)
            else:
                self._dispatch_gated(inner)
        finally:
            set_request_context(None)
            wfile = self.wfile
            if isinstance(wfile, _TruncatingWriter):
                wfile.finish_request()

    def _content_length(self) -> int:
        """Malformed Content-Length must not traceback the admission
        path (it runs before auth for every request): treat it as 0 AND
        drop keep-alive — the framing of any body the client did send
        is unknowable, so its unread bytes must not corrupt the next
        request on this connection. Every consumer (admission width,
        the 429 drain, ``_read_body``) routes through here, so the
        close decision is made exactly once."""
        try:
            return int(self.headers.get("Content-Length") or 0)
        except ValueError:
            self.close_connection = True
            return 0

    def _send_429(self, message: str, retry_after: float,
                  level: str = "", schema: str = "",
                  epoch: Optional[int] = None) -> None:
        """Overload pushback with an HONEST Retry-After (the level's or
        lane's expected drain time) plus the rejecting priority level /
        flow schema headers the client's retry accounting keys on
        (reference X-Kubernetes-PF-* response headers). ``epoch`` rides
        as X-Partition-Epoch when the rejection is topology-shaped (a
        frozen or moved keyspace slice): a stale router refreshes its
        topology and re-routes instead of hammering the wrong shard."""
        # drain the body first so keep-alive framing stays intact for
        # the client's retry (same discipline as the injected-fault 429)
        # — unless a handler already consumed it (the reshard gate
        # fires after _read_body; a second read here would block on
        # bytes that will never come)
        length = self._content_length()
        if length and not getattr(self, "_body_consumed", False):
            self.rfile.read(length)
        body = json.dumps({
            "kind": "Status", "status": "Failure",
            "reason": "TooManyRequests",
            "message": message,
            "code": 429,
        }).encode()
        self.send_response(429)
        self.send_header("Retry-After", f"{retry_after:g}")
        if level:
            self.send_header("X-Kubernetes-PF-PriorityLevel", level)
        if schema:
            self.send_header("X-Kubernetes-PF-FlowSchema", schema)
        if epoch is not None:
            self.send_header("X-Partition-Epoch", str(int(epoch)))
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _run_inner(self, inner) -> None:
        try:
            inner()
        except Forbidden as e:
            # raised before any bytes were written (body reads precede
            # every send): e.g. a binary body from an unauthenticated
            # client
            self._send_error(403, "Forbidden", str(e))

    def _dispatch_gated(self, inner) -> None:
        fc = self.server.flowcontrol
        if fc is not None:
            self._dispatch_apf(fc, inner)
            return
        gated = self._gate()
        if gated is None:
            self._run_inner(inner)
            return
        lane, stats = gated
        if not lane.acquire(blocking=False):
            self._send_429(
                "too many requests in flight, try again later",
                stats.retry_after())
            return
        stats.start()
        t0 = time.monotonic()
        try:
            self._run_inner(inner)
        finally:
            lane.release()
            stats.done(time.monotonic() - t0)

    # -- API Priority & Fairness admission (flowcontrol.py; reference
    # filters/priority-and-fairness.go): the default admission path.
    # FlowSchemas route identity/verb/resource to a priority level;
    # the level's shuffle-sharded queueset fairly queues or rejects.
    def _dispatch_apf(self, fc: FlowController, inner) -> None:
        path = self.path.split("?", 1)[0]
        if self._admission_exempt(path):
            self._run_inner(inner)
            return
        user = self._user()
        groups_fn = getattr(self.server.authorizer, "groups_for", None)
        groups = groups_fn(user) if groups_fn is not None else ()
        is_watch = self.command in ("GET", "HEAD") \
            and "watch=" in self.path
        try:
            items_hint = int(
                self.headers.get("X-Kubernetes-Request-Items") or 0)
        except ValueError:
            items_hint = 0
        # the flow distinguisher is SERVER-derived (identity/namespace,
        # as upstream insists): X-Flow-Id may refine it only from the
        # control-plane trust envelope (_binary_decode_allowed — system
        # identities or the loopback escape hatch). An untrusted tenant
        # minting a fresh distinguisher per request would become a new
        # flow per request, hash across every queue in its level, and
        # shred the shuffle-shard isolation this subsystem exists for.
        flow_id = self.headers.get("X-Flow-Id") or ""
        if flow_id and not self._binary_decode_allowed():
            flow_id = ""
        try:
            ticket = fc.admit(
                user=user, groups=groups or (), verb=self.command,
                resource=resource_of(self.path),
                namespace=namespace_of(self.path),
                flow_id=flow_id,
                items_hint=items_hint,
                content_length=self._content_length(),
                is_watch=is_watch, path=self.path)
        except Rejected as rej:
            self._send_429(
                f"too many requests for priority level {rej.level!r} "
                f"({rej.reason}), try again later",
                rej.retry_after, level=rej.level, schema=rej.schema)
            return
        # watches release their watch-init seats right after the stream
        # attaches (_serve_watch); everything else releases here
        self._apf_ticket = ticket
        try:
            self._run_inner(inner)
        finally:
            self._apf_ticket = None
            ticket.release()

    def _send_json(self, code: int, payload: Any) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self._send_codec_header()
        self.end_headers()
        self.wfile.write(body)

    def _send_codec_header(self) -> None:
        """Echo the pinned wire version (codec.negotiate) on every
        response so the client records/renegotiates across restart
        seams; call between send_response and end_headers."""
        from kubernetes_tpu.apiserver import codec

        self.send_header(
            codec.VERSION_HEADER,
            str(getattr(self, "_codec_version", codec.CODEC_VERSION)))

    def _send_error(self, code: int, reason: str, message: str) -> None:
        # reference metav1.Status error envelope
        self._send_json(
            code,
            {
                "kind": "Status",
                "status": "Failure",
                "reason": reason,
                "message": message,
                "code": code,
            },
        )

    # -- binary codec negotiation (codec.py: the protobuf analog) ------
    def _accepts_binary(self) -> bool:
        from kubernetes_tpu.apiserver import codec

        return codec.BINARY_CONTENT_TYPE in (self.headers.get("Accept") or "")

    # identities allowed to speak the binary codec: the control plane
    # itself (codec.py's trust envelope — "kubelet/scheduler/
    # controller-manager speak it, kubectl speaks JSON"); a mere
    # authenticated namespace SA token must NOT reach the unpickler
    _BINARY_PREFIXES = ("system:kube-", "system:node:")

    def _binary_decode_allowed(self) -> bool:
        """Pickle bodies only from CONTROL-PLANE identities — codec.py's
        trust model. The no-authn escape hatch requires a LOOPBACK
        peer: a tokenless server bound to a reachable interface must
        not be an arbitrary-code-execution endpoint."""
        if not self.server.tokens and self.server.authorizer is allow_all:
            peer = self.client_address[0] if self.client_address else ""
            return peer in ("127.0.0.1", "::1", "::ffff:127.0.0.1")
        user = self._user()
        if user.startswith(self._BINARY_PREFIXES):
            return True
        if user in self.server.binary_clients:
            return True
        groups = getattr(self.server.authorizer, "groups_for", None)
        return groups is not None and "system:masters" in groups(user)

    def _read_body(self) -> Any:
        length = self._content_length()
        raw = self.rfile.read(length) if length else b"{}"
        self._body_consumed = True
        ctype = self.headers.get("Content-Type") or ""
        from kubernetes_tpu.apiserver import codec

        if ctype.startswith(codec.BINARY_CONTENT_TYPE):
            if not self._binary_decode_allowed():
                raise Forbidden(
                    "binary bodies require an authenticated client")
            return codec.decode(raw)
        return json.loads(raw or b"{}")

    def _send_bytes(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self._send_codec_header()
        self.end_headers()
        self.wfile.write(body)

    def _send_negotiated(self, code: int, payload: Any,
                         json_fallback: Optional[Callable[[], Any]] = None
                         ) -> None:
        """Send ``payload`` pickled when the client asked for binary;
        otherwise the JSON shape (``json_fallback()`` when the JSON wire
        differs from the binary payload, e.g. objects vs dicts)."""
        from kubernetes_tpu.apiserver import codec

        if self._accepts_binary():
            self._send_bytes(code, codec.encode(payload),
                             codec.BINARY_CONTENT_TYPE)
        else:
            self._send_json(
                code, json_fallback() if json_fallback else payload)

    # -- versioned codec (scheme hub-and-spoke) ------------------------
    def _decode(self, body: Dict, kind: str) -> Any:
        from kubernetes_tpu.api.scheme import SCHEME_V

        api_version = getattr(self, "_api_version", "v1")
        if self.server.store.custom_kind_to_plural(kind):
            # custom kinds: None-conversion — every served version
            # decodes the same payload (apiextensions default strategy)
            return from_wire(body, kind)
        return SCHEME_V.decode(body, kind, api_version)

    def _encode(self, obj: Any) -> Dict:
        from kubernetes_tpu.api.scheme import SCHEME_V
        from kubernetes_tpu.api.types import CustomObject

        api_version = getattr(self, "_api_version", "v1")
        if isinstance(obj, CustomObject):
            return _encode_custom(obj, api_version)
        return SCHEME_V.encode(obj, api_version)

    # -- authn/authz ---------------------------------------------------
    def _user(self) -> str:
        auth = self.headers.get("Authorization") or ""
        if auth.startswith("Bearer "):
            token = auth[len("Bearer "):].strip()
            user = self.server.tokens.get(token)
            if user is not None:
                return user
            # token→identity LRU: a resolved SA/cert identity must not
            # re-pay the index lookups and liveness checks per request;
            # invalidated by Secret/ServiceAccount/CSR events (the only
            # mutations that can change a resolution)
            cache = self.server._token_cache
            user = cache.get(token)
            if user is not None:
                return user
            # CSR-issued client certificates authenticate by
            # fingerprint (the x509 request authenticator's role,
            # reference apiserver/pkg/authentication/request/x509/
            # x509.go CommonNameUserConversion — fingerprint-as-bearer
            # stands in for the TLS handshake)
            if token.startswith("cert:"):
                user = self.server.resolve_cert_fingerprint(
                    token[len("cert:"):])
                if user is not None:
                    self.server._cache_token(token, user, cache)
                    return user
            # service-account tokens (minted by the tokens controller)
            # authenticate as system:serviceaccount:<ns>:<name> —
            # reference pkg/serviceaccount token authenticator
            user = self.server.resolve_sa_token(token)
            if user is not None:
                self.server._cache_token(token, user, cache)
                return user
            # failures are never cached: an unknown-token flood must
            # not evict resolved identities
            return f"token:{token[:8]}"
        return "system:anonymous"

    def _check_authz(self, verb: str, kind: str, namespace: str) -> str:
        user = self._user()
        if not self.server.authorize_cached(user, verb, kind, namespace):
            raise Forbidden(f"user {user!r} cannot {verb} {kind}")
        return user

    # -- discovery (the client-go RESTMapper's server half:
    # staging/src/k8s.io/apiserver/pkg/endpoints/discovery) -----------
    @staticmethod
    def _is_discovery_path(path: str) -> bool:
        parts = [p for p in path.split("/") if p]
        return (
            (len(parts) == 2 and parts[0] == "api" and parts[1] == "v1")
            or (len(parts) == 3 and parts[0] == "apis")
        )

    def _serve_discovery(self, path: str) -> None:
        from kubernetes_tpu.api.scheme import SCHEME_V
        from kubernetes_tpu.api.serialization import CLUSTER_SCOPED

        parts = [p for p in path.split("/") if p]
        if path == "/api":
            self._send_json(200, {"kind": "APIVersions",
                                  "versions": ["v1"]})
            return
        if path == "/apis":
            groups: Dict[str, list] = {}
            for (gv, _kind) in SCHEME_V._spokes:
                group, _, version = gv.partition("/")
                if version not in groups.setdefault(group, []):
                    groups[group].append(version)
            # live CRD groups join discovery at their served versions
            store = self.server.store
            for kind in store.custom_kind_names():
                group, served = store.custom_served_versions(kind)
                if group:
                    for v in served:
                        if v not in groups.setdefault(group, []):
                            groups[group].append(v)

            def version_priority(v: str):
                # kube version ordering (apimachinery version.
                # CompareKubeAwareVersionStrings): GA > beta > alpha,
                # then numeric — "v1" must beat "v1beta1"
                import re

                m = re.match(r"^v(\d+)(alpha|beta)?(\d+)?$", v)
                if not m:
                    return (0, 0, 0)
                stage = {"alpha": 1, "beta": 2, None: 3}[m.group(2)]
                return (stage, int(m.group(1)), int(m.group(3) or 0))

            def ordered(vs):
                return sorted(vs, key=version_priority, reverse=True)

            self._send_json(200, {
                "kind": "APIGroupList",
                "groups": [
                    {
                        "name": g,
                        "versions": [
                            {"groupVersion": f"{g}/{v}", "version": v}
                            for v in ordered(vs)
                        ],
                        "preferredVersion": {
                            "groupVersion": f"{g}/{ordered(vs)[0]}",
                            "version": ordered(vs)[0],
                        },
                    }
                    for g, vs in sorted(groups.items())
                ],
            })
            return
        if parts[0] == "api":                       # /api/v1
            resources = [
                {"name": plural, "kind": kind,
                 "namespaced": kind not in CLUSTER_SCOPED}
                for plural, kind in sorted(PLURALS.items())
            ]
            # CRD-registered kinds are part of live discovery
            store = self.server.store
            for kind in store.custom_kind_names():
                plural = store.custom_kind_to_plural(kind)
                if plural:
                    resources.append({
                        "name": plural, "kind": kind,
                        "namespaced": store.kind_is_namespaced(kind),
                    })
            self._send_json(200, {
                "kind": "APIResourceList", "groupVersion": "v1",
                "resources": resources,
            })
            return
        gv = f"{parts[1]}/{parts[2]}"               # /apis/<g>/<v>
        kinds = SCHEME_V.kinds_for(gv)
        resources = [
            {"name": KIND_TO_PLURAL.get(k, k.lower() + "s"),
             "kind": k,
             "namespaced": k not in CLUSTER_SCOPED}
            for k in sorted(kinds)
        ]
        store = self.server.store
        for kind in store.custom_kind_names():
            group, served = store.custom_served_versions(kind)
            if group == parts[1] and parts[2] in served:
                resources.append({
                    "name": store.custom_kind_to_plural(kind),
                    "kind": kind,
                    "namespaced": store.kind_is_namespaced(kind),
                })
        if not resources:
            self._send_error(404, "NotFound", f"no group/version {gv!r}")
            return
        self._send_json(200, {
            "kind": "APIResourceList", "groupVersion": gv,
            "resources": resources,
        })

    # -- routing -------------------------------------------------------
    def _route(self) -> Tuple[Optional[str], Optional[str], Optional[str], Optional[str], Dict]:
        """→ (kind, namespace, name, subresource, query). Also resolves
        the request's apiVersion into ``self._api_version``: the legacy
        core path ``/api/v1`` serves the internal hub shape; group
        routes ``/apis/<group>/<version>`` serve versioned spokes
        through the scheme's conversion/defaulting (reference
        InstallLegacyAPI vs InstallAPIs, ``pkg/controlplane/
        instance.go:547,580``)."""
        from kubernetes_tpu.api.scheme import SCHEME_V

        u = urlparse(self.path)
        q = {k: v[0] for k, v in parse_qs(u.query).items()}
        parts = [p for p in u.path.split("/") if p]
        self._api_version = "v1"
        if len(parts) >= 3 and parts[0] == "apis":
            api_version = f"{parts[1]}/{parts[2]}"
            rest = parts[3:]
            ns: Optional[str] = None
            if rest and rest[0] == "namespaces" and len(rest) >= 2:
                ns = rest[1]
                rest = rest[2:]
            if not rest:
                return None, ns, None, None, q
            kind = PLURALS.get(rest[0])
            if kind is None or not SCHEME_V.recognizes(api_version, kind):
                # CRD group routes: /apis/<group>/<version>/<plural>
                # serves a custom kind at every version its CRD
                # declares served (multi-version, None-conversion)
                kind = self.server.store.custom_route(
                    parts[1], parts[2], rest[0])
                if kind is None:
                    return None, None, None, None, q
            self._api_version = api_version
            name = rest[1] if len(rest) >= 2 else None
            sub = rest[2] if len(rest) >= 3 else None
            return kind, ns, name, sub, q
        # legacy core: /api/v1/...
        if len(parts) < 2 or parts[0] != "api" or parts[1] != "v1":
            return None, None, None, None, q
        rest = parts[2:]
        ns: Optional[str] = None
        if rest and rest[0] == "namespaces" and len(rest) >= 2:
            ns = rest[1]
            rest = rest[2:]
        if not rest:
            return None, ns, None, None, q
        kind = PLURALS.get(rest[0])
        if kind is None:
            # CRD-registered plurals resolve through the store's live
            # registry (apiextensions: a new CRD IS a new route)
            kind = self.server.store.custom_plural_to_kind(rest[0])
        name = rest[1] if len(rest) >= 2 else None
        sub = rest[2] if len(rest) >= 3 else None
        return kind, ns, name, sub, q

    # -- verbs ---------------------------------------------------------
    def do_GET(self) -> None:
        self._handle_gated(self._do_GET)

    def _dispatch_admin(self, verb: str) -> bool:
        """Route an admin path through the ADMIN_ROUTES registry.
        True = the request was an admin request and has been answered."""
        handler = self.ADMIN_ROUTES.get(urlparse(self.path).path)
        if handler is None:
            return False
        getattr(self, handler)(verb)
        return True

    def _serve_trace_admin(self, verb: str) -> None:
        """/debug/trace: the flight recorder's control surface. GET →
        Chrome/Perfetto trace_event JSON of the trailing retention
        window (``?window=SECONDS`` overrides it); DELETE → clear the
        ring. Same control-plane trust envelope as /debug/faults, and
        like it exempt from FaultGate and the in-flight lanes (via
        ADMIN_ROUTES) — the dump must be reachable exactly when the
        server is sick."""
        if not self._binary_decode_allowed():
            self._send_error(403, "Forbidden",
                             "trace admin requires a control-plane identity")
            return
        tracer = self.server.tracer
        if tracer is None or not tracer.enabled:
            # KTPU_TRACE=off yields a disabled (never None) tracer: an
            # explicit 404 beats a 200 empty dump an operator can't
            # tell apart from "nothing happened in the last 60s"
            self._send_error(404, "NotFound", "tracing is not enabled")
            return
        if verb == "GET":
            q = {k: v[0] for k, v in
                 parse_qs(urlparse(self.path).query).items()}
            window = None
            if q.get("window"):
                try:
                    window = float(q["window"])
                except ValueError:
                    self._send_error(400, "BadRequest",
                                     f"invalid window {q['window']!r}")
                    return
            doc = tracer.export_perfetto(window)
            # half-RTT clock-offset echo (TraceFederation): the scraper
            # sends its monotonic send-time as ?echo_mono=; we echo it
            # beside OUR monotonic clock at export so the scraper can
            # place this process's spans on its own timeline with a
            # bounded-skew correction (bound = rtt/2).
            if q.get("echo_mono"):
                try:
                    doc["otherData"]["echo_mono"] = float(q["echo_mono"])
                except ValueError:
                    pass
            doc["otherData"]["server_mono"] = time.monotonic()
            self._send_json(200, doc)
            return
        if verb == "DELETE":
            tracer.clear()
            self._send_json(200, {"kind": "Status", "status": "Success"})
            return
        self._send_error(405, "MethodNotAllowed",
                         "/debug/trace supports GET and DELETE")

    def _serve_apf_admin(self, verb: str) -> None:
        """/debug/apf: API Priority & Fairness introspection. GET → the
        FlowController snapshot (per-level seats/queues/rejections/
        flows, schema match counts, shed state). Same control-plane
        trust envelope as the other debug surfaces, and — via
        ADMIN_ROUTES — exempt from admission itself: the overload
        postmortem must be readable mid-overload."""
        if not self._binary_decode_allowed():
            self._send_error(403, "Forbidden",
                             "apf admin requires a control-plane identity")
            return
        fc = self.server.flowcontrol
        if fc is None:
            self._send_error(404, "NotFound",
                             "flow control is not enabled (legacy "
                             "max-in-flight lanes are active)")
            return
        if verb != "GET":
            self._send_error(405, "MethodNotAllowed",
                             "/debug/apf supports GET")
            return
        self._send_json(200, fc.snapshot())

    def _serve_slo_admin(self, verb: str) -> None:
        """/debug/slo: live SLO evaluation (observability/slo.py). GET
        → every declared SLO's windowed SLI, burn rates, and verdicts
        for THIS process. Same control-plane trust envelope as the
        other debug surfaces and — via ADMIN_ROUTES — exempt from
        admission: the burn-rate postmortem must be readable exactly
        when the fabric is violating its objectives."""
        if not self._binary_decode_allowed():
            self._send_error(403, "Forbidden",
                             "slo admin requires a control-plane identity")
            return
        if verb != "GET":
            self._send_error(405, "MethodNotAllowed",
                             "/debug/slo supports GET")
            return
        from kubernetes_tpu.observability.slo import get_slo_engine

        engine = get_slo_engine()
        if not engine.enabled:
            self._send_error(404, "NotFound",
                             "SLO evaluation is not enabled (KTPU_SLO=off)")
            return
        self._send_json(200, engine.evaluate())

    def _serve_faults_admin(self, verb: str) -> None:
        """/debug/faults: runtime fault-injection control surface.
        GET → config + injection counters; POST/PUT → replace rule set
        (``{"seed": S, "rules": [...]}``); DELETE → clear. Guarded by
        the binary codec's control-plane trust envelope: loopback on a
        tokenless server, control-plane identity otherwise — an
        ordinary namespace token must not be able to break the wire."""
        if not self._binary_decode_allowed():
            self._send_error(403, "Forbidden",
                             "fault admin requires a control-plane identity")
            return
        gate = self.server.fault_gate
        if verb == "GET":
            self._send_json(200, gate.snapshot())
            return
        if verb == "DELETE":
            gate.clear()
            self._send_json(200, {"kind": "Status", "status": "Success"})
            return
        if verb not in ("POST", "PUT"):
            self._send_error(405, "MethodNotAllowed",
                             "/debug/faults supports GET, POST, PUT, "
                             "and DELETE")
            return
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length) if length else b"{}"
        try:
            gate.configure(json.loads(raw or b"{}"))
        except (ValueError, TypeError) as e:
            self._send_error(400, "BadRequest", f"invalid fault spec: {e}")
            return
        self._send_json(200, gate.snapshot())

    # -- elastic control plane: the freeze/ownership write gate --------
    def _reshard_verdict(self, kind: str, ns: Optional[str],
                         name: Optional[str]) -> Optional[tuple]:
        """Judge one mutation against the live partition topology.
        None = allowed (and counted on the slot-write ledger the
        rebalancer reads); ("frozen", retry_after) = the slice is
        inside a migration's freeze window; ("stale", epoch) = this
        server no longer owns the slice — the caller's routing table
        predates epoch."""
        server = self.server
        topo = server.partition_topology
        if topo is None:
            return None
        slot = topo.slot_of(kind, ns, name)
        if slot is None:
            return None
        frozen = server.frozen_slots.get(slot)
        if frozen is not None:
            deadline, _eta = frozen
            remaining = deadline - time.monotonic()
            if remaining > 0:
                return ("frozen", max(0.05, remaining))
            server.frozen_slots.pop(slot, None)   # auto-thaw backstop
        if topo.owner[slot] != server.partition_index:
            return ("stale", topo.epoch)
        server.slot_writes[slot] = server.slot_writes.get(slot, 0) + 1
        if kind == "Pod" and ns:
            server.ns_writes[ns] = server.ns_writes.get(ns, 0) + 1
        return None

    def _reshard_gate(self, kind: Optional[str], ns: Optional[str],
                      name: Optional[str]) -> bool:
        """Answer a topology-shaped 429 for a gated mutation. True =
        the request was answered (frozen slice: computed Retry-After so
        the client's existing pushback loop simply pauses through the
        freeze window; moved slice: the new epoch so the client
        refreshes its routing and re-sends to the owner)."""
        if kind is None or self.server.partition_topology is None:
            return False
        verdict = self._reshard_verdict(kind, ns, name)
        if verdict is None:
            return False
        if verdict[0] == "frozen":
            # NO epoch header: frozen means the caller's routing is
            # CORRECT and the only cure is waiting out the advertised
            # window — the epoch header is the re-route signal and
            # would send clients re-splitting a batch that maps to
            # exactly the same frozen slice
            self._send_429(
                f"{kind} {ns or ''}/{name or ''}: keyspace slice frozen "
                f"by a live partition migration",
                verdict[1], level="reshard")
        else:
            self._send_429(
                f"{kind} {ns or ''}/{name or ''}: slice moved — this "
                f"server no longer owns it (topology epoch "
                f"{verdict[1]})",
                0.05, level="reshard", epoch=verdict[1])
        return True

    def _reshard_gate_bulk(self, kind: str, keys) -> bool:
        """Gate a bulk verb: every (ns, name) must be owned and thawed
        BEFORE any item mutates state — a half-applied bulk request
        under a topology flip would be exactly the torn write the
        freeze protocol exists to prevent. Worst verdict wins (stale
        beats frozen: re-routing supersedes waiting)."""
        if self.server.partition_topology is None:
            return False
        worst: Optional[tuple] = None
        for ns, name in keys:
            verdict = self._reshard_verdict(kind, ns, name)
            if verdict is None:
                continue
            if verdict[0] == "stale":
                worst = verdict
                break
            worst = worst or verdict
        if worst is None:
            return False
        if worst[0] == "frozen":
            # no epoch header — see _reshard_gate: frozen = wait, the
            # routing is already right
            self._send_429(
                f"bulk {kind} batch touches a keyspace slice frozen by "
                f"a live partition migration", worst[1], level="reshard")
        else:
            self._send_429(
                f"bulk {kind} batch touches a moved slice (topology "
                f"epoch {worst[1]})", 0.05, level="reshard",
                epoch=worst[1])
        return True

    def _serve_partition_admin(self, verb: str) -> None:
        """/debug/partition: the live-resharding control surface the
        ReshardCoordinator drives — freeze/unfreeze keyspace slices,
        read a slice out, adopt/evict objects (the silent placement
        channel), install a new topology, and inspect the slot-write
        ledger. Control-plane trust envelope; exempt from flow control
        and the FaultGate like every admin route (a migration must stay
        drivable while the fabric is sick — that is its point)."""
        if not self._binary_decode_allowed():
            self._send_error(403, "Forbidden",
                             "partition admin requires a control-plane "
                             "identity")
            return
        server = self.server
        if verb == "GET":
            topo = server.partition_topology
            store = server.store
            with store._lock:
                objects = sum(
                    len(getattr(store, attr))
                    for attr, _ in store._KIND_TABLES.values())
                mutations = sum(store._kind_seq.values())
            now = time.monotonic()
            self._send_json(200, {
                "partition": server.partition_index,
                "partitions": server.partition_count,
                "epoch": topo.epoch if topo is not None else 0,
                "topology": topo.to_dict() if topo is not None else None,
                "frozen": sorted(
                    s for s, (dl, _e) in server.frozen_slots.items()
                    if dl > now),
                "slot_writes": {str(k): v
                                for k, v in server.slot_writes.items()},
                "ns_writes": dict(server.ns_writes),
                "objects": objects,
                "mutations": mutations,
            })
            return
        if verb != "POST":
            self._send_error(405, "MethodNotAllowed",
                             "/debug/partition supports GET and POST")
            return
        try:
            body = self._read_body()
        except json.JSONDecodeError as e:
            self._send_error(400, "BadRequest", f"invalid JSON: {e}")
            return
        if not isinstance(body, dict):
            self._send_error(400, "BadRequest", "op body required")
            return
        op = body.get("op")
        try:
            if op == "freeze":
                eta = float(body.get("eta") or 5.0)
                deadline = time.monotonic() + eta
                for s in body.get("slots") or ():
                    server.frozen_slots[int(s)] = (deadline, eta)
                self._send_json(200, {"frozen": sorted(
                    int(s) for s in body.get("slots") or ())})
            elif op == "unfreeze":
                slots = body.get("slots")
                if slots is None:
                    server.frozen_slots.clear()
                else:
                    for s in slots:
                        server.frozen_slots.pop(int(s), None)
                self._send_json(200, {"frozen": sorted(
                    server.frozen_slots)})
            elif op == "topology":
                from kubernetes_tpu.apiserver.partition import (
                    PartitionTopology,
                )

                doc = body.get("topology") or {}
                installed = server.install_topology(
                    PartitionTopology.from_dict(doc))
                self._send_json(200, {
                    "installed": installed,
                    "epoch": server.partition_topology.epoch
                    if server.partition_topology else 0})
            elif op == "slice":
                slots = {int(s) for s in body.get("slots") or ()}
                spread = frozenset(body.get("spread") or ())
                slot_count = int(body.get("slot_count") or 0)
                out = self._collect_slice(slots, spread, slot_count,
                                          body.get("namespace"))
                self._send_json(200, {
                    "objects": {k: [to_wire(o) for o in objs]
                                for k, objs in out.items()}})
            elif op == "adopt":
                counts = {}
                for kind, items in (body.get("objects") or {}).items():
                    objs = [from_wire(w, kind) for w in items]
                    counts[kind] = server.store.adopt_objects(kind, objs)
                server.invalidate_list_caches()
                self._send_json(200, {"adopted": counts})
            elif op == "evict":
                counts = {}
                for kind, keys in (body.get("keys") or {}).items():
                    got = server.store.evict_objects(
                        kind, [(k[0], k[1]) for k in keys])
                    counts[kind] = len(got)
                server.invalidate_list_caches()
                self._send_json(200, {"evicted": counts})
            elif op == "evict_unowned":
                # post-crash reconciliation: silently drop every
                # sharded object this server does not own under the
                # committed topology (orphan copies from a torn
                # migration — the owner holds the live ones)
                topo = server.partition_topology
                if topo is None:
                    self._send_json(200, {"evicted": {}})
                    return
                counts = {}
                from kubernetes_tpu.apiserver.partition import (
                    SHARDED_CLUSTER_KINDS,
                    SHARDED_NAMESPACED_KINDS,
                )

                for kind in (tuple(SHARDED_NAMESPACED_KINDS)
                             + tuple(SHARDED_CLUSTER_KINDS)):
                    attr, _ = server.store._KIND_TABLES[kind]
                    with server.store._lock:
                        doomed = [
                            (o.metadata.namespace, o.metadata.name)
                            for o in getattr(server.store, attr).values()
                            if topo.partition_of(
                                kind, o.metadata.namespace,
                                o.metadata.name)
                            != server.partition_index]
                    if doomed:
                        got = server.store.evict_objects(kind, doomed)
                        counts[kind] = len(got)
                server.invalidate_list_caches()
                self._send_json(200, {"evicted": counts})
            else:
                self._send_error(400, "BadRequest",
                                 f"unknown partition op {op!r}")
        except (ValueError, TypeError, KeyError) as e:
            self._send_error(400, "BadRequest",
                             f"partition op {op!r} failed: {e}")

    def _collect_slice(self, slots, spread, slot_count,
                       namespace: Optional[str] = None) -> Dict[str, list]:
        """Objects in the given hash slots (both sharded kinds), read
        under the store lock — the copy half of a slice migration. The
        SPREAD set and slot count come from the PROPOSED topology: a
        split must cut the slice exactly where the new routing will.
        ``namespace`` narrows a split's copy to the spreading tenant."""
        from kubernetes_tpu.apiserver.partition import (
            NUM_SLOTS,
            SHARDED_CLUSTER_KINDS,
            SHARDED_NAMESPACED_KINDS,
            slot_for,
        )

        slot_count = slot_count or NUM_SLOTS
        store = self.server.store
        out: Dict[str, list] = {}
        with store._lock:
            for kind in (tuple(SHARDED_NAMESPACED_KINDS)
                         + tuple(SHARDED_CLUSTER_KINDS)):
                if namespace is not None \
                        and kind not in SHARDED_NAMESPACED_KINDS:
                    continue   # a namespace split never moves Nodes
                attr, _ = store._KIND_TABLES[kind]
                got = [
                    o for o in getattr(store, attr).values()
                    if (namespace is None
                        or o.metadata.namespace == namespace)
                    and slot_for(kind, o.metadata.namespace,
                                 o.metadata.name, slot_count,
                                 spread) in slots]
                if got:
                    out[kind] = got
        return out

    def _do_GET(self) -> None:
        u = urlparse(self.path)
        if self._dispatch_admin("GET"):
            return
        if u.path in ("/healthz", "/livez", "/readyz"):
            body = b"ok"
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if u.path == "/metrics":
            text = self.server.metrics_text()
            body = text.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if u.path == "/api/v1/partitiontopology":
            # partition identity: which shard of the partitioned control
            # plane this server is, and how many exist — the client-side
            # router's sanity check (a misrouted client fails loudly
            # instead of silently reading a half-empty shard). Exempt
            # like the health probes: topology must be discoverable
            # even mid-overload. With a LIVE topology installed (the
            # elastic control plane) the full routing document rides
            # along — epoch, slot owners, spread namespaces, endpoint
            # urls — so clients re-route on an epoch change without any
            # side channel; servers predating resharding keep the exact
            # legacy two-field shape.
            doc = {
                "partition": self.server.partition_index,
                "partitions": self.server.partition_count,
            }
            topo = self.server.partition_topology
            if topo is not None:
                doc.update(topo.to_dict())
            self._send_json(200, doc)
            return
        if u.path == "/api/v1/subscription":
            # read-tier commit stream (apiserver/readtier.py): the
            # owner's whole event history as one all-kind feed, resumed
            # by resourceVersion — a control-plane internal surface in
            # the same trust envelope as the topology doc
            self._serve_subscription(u)
            return
        if u.path in ("/api", "/apis") or self._is_discovery_path(u.path):
            self._serve_discovery(u.path)
            return
        if u.path == "/metrics/resources":
            # reference cmd/kube-scheduler/app/server.go:243 +
            # pkg/scheduler/metrics/resources: per-pod resource
            # requests/limits as kube_pod_resource_* gauges
            body = resources_metrics_text(self.server.store).encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if self.server.fenced.is_set():
            # self-fenced read replica: past its replication-lag budget,
            # so serving this read would violate the staleness contract.
            # A distinguishable 503 (X-Replica-Fenced) tells the client
            # to re-route the read to a sibling replica or the owner;
            # health probes, metrics, and the topology doc above stay
            # reachable so the fence itself remains observable.
            body = json.dumps({
                "kind": "Status", "status": "Failure",
                "reason": "ReplicaFenced", "code": 503,
                "message": "read replica fenced: replication lag over "
                           "budget — re-route to a sibling or the owner",
            }).encode()
            self.send_response(503)
            self.send_header("Content-Type", "application/json")
            self.send_header("X-Replica-Fenced", "1")
            self.send_header("Retry-After", "0.5")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        kind, ns, name, sub, q = self._route()
        if kind is None:
            self._send_error(404, "NotFound", f"no route for {self.path}")
            return
        try:
            if kind == "Pod" and sub == "log":
                # pods/log is a distinct RBAC resource in the reference
                # (a role granting only "get pods" must not leak logs)
                self._check_authz("get", "pods/log", ns or "")
            else:
                self._check_authz("get" if name else "list", kind, ns or "")
        except Forbidden as e:
            self._send_error(403, "Forbidden", str(e))
            return
        store = self.server.store
        # selectors parse BEFORE the list/watch split: both paths honor
        # them, and both reject unsupported fields with 400
        label_sel = None
        field_checks = None
        if q.get("labelSelector"):
            from kubernetes_tpu.api.labels import parse_selector

            try:
                label_sel = parse_selector(q["labelSelector"])
            except Exception as e:  # noqa: BLE001 — grammar error
                self._send_error(400, "BadRequest",
                                 f"invalid labelSelector: {e}")
                return
        if q.get("fieldSelector"):
            try:
                field_checks = _parse_field_selector(
                    kind, q["fieldSelector"])
            except ValueError as e:
                self._send_error(400, "BadRequest", str(e))
                return
        if q.get("watch") in ("true", "1"):
            try:
                rv = int(q.get("resourceVersion") or 0)
            except ValueError:
                self._send_error(
                    400, "BadRequest",
                    f"invalid resourceVersion {q.get('resourceVersion')!r}",
                )
                return
            self._serve_watch(kind, ns, rv, label_sel, field_checks)
            return
        if kind == "Pod" and sub == "log" and name is not None:
            # pods/log subresource: proxy to the owning node's kubelet
            # (reference registry/core/pod/rest/log.go -> kubelet
            # /containerLogs); authz'd above as its own "pods/log"
            # resource — "get pods" alone must not leak logs
            pod = store.get_pod(ns or "default", name)
            if pod is None:
                self._send_error(404, "NotFound", f"pod {name!r} not found")
                return
            source = store.log_source(pod.spec.node_name) \
                if pod.spec.node_name else None
            if source is None:
                self._send_error(
                    404, "NotFound",
                    f"no log source for node {pod.spec.node_name!r} "
                    "(pod not running on a registered kubelet)",
                )
                return
            try:
                text = source(ns or "default", name,
                              q.get("container", ""))
            except LookupError as e:
                # unknown container / pod not yet synced on the node:
                # the client's fault, never silent-empty success
                self._send_error(400, "BadRequest", str(e))
                return
            except Exception as e:  # noqa: BLE001 — kubelet-side failure
                self._send_error(500, "InternalError", str(e))
                return
            body = text.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if name is not None:
            obj = store.get_object(kind, ns or "default", name)
            if obj is None:
                self._send_error(404, "NotFound", f"{kind} {name!r} not found")
                return
            self._send_negotiated(200, obj,
                                  json_fallback=lambda: self._encode(obj))
            return
        # list + RV atomically: a watch from this RV misses nothing.
        # Selector-free binary lists serve from the per-kind pre-encoded
        # cache — the hot reflector path pays no per-request encode.
        # Leases are excluded: renewals mutate lease state without a
        # dispatch, so kind_seq cannot validate a cached body for them.
        if label_sel is None and field_checks is None \
                and kind != "Lease" and self._accepts_binary():
            from kubernetes_tpu.apiserver import codec

            self._send_bytes(200, self.server.cached_list_binary(kind, ns),
                             codec.BINARY_CONTENT_TYPE)
            return
        objs, rv = store.list_objects_with_rv(kind, ns)
        fc = self.server.flowcontrol
        if fc is not None:
            # feed width estimation: the NEXT list of this resource
            # charges seats proportional to what this one served
            fc.width.note_list_size(resource_of(self.path), len(objs))
        if label_sel is not None:
            objs = [o for o in objs
                    if label_sel.matches(o.metadata.labels)]
        if field_checks is not None:
            objs = [o for o in objs
                    if _field_checks_match(o, field_checks)]
        self._send_negotiated(
            200,
            {"kind": f"{kind}List", "resourceVersion": rv, "items": objs},
            json_fallback=lambda: {
                "kind": f"{kind}List",
                "apiVersion": getattr(self, "_api_version", "v1"),
                "metadata": {"resourceVersion": str(rv)},
                "items": [self._encode(o) for o in objs],
            },
        )

    def _bulk_bindings(self, ns: Optional[str]) -> None:
        """POST .../bindings with a BindingList: the batch-native wire
        for the TPU commit path — one request, one store lock, one
        batched watch delivery for N bindings (store.bind_many). Each
        item is still its own transaction with the exact per-pod
        semantics of POST pods/{name}/binding (reference
        storage.go:159 BindingREST.Create); failures come back
        positionally. The reference has no bulk verb — its Go scheduler
        amortizes with 64 goroutines instead; a batch scheduler that
        solves 4096 placements per device call would serialize on
        per-pod round trips."""
        try:
            body = self._read_body()
        except json.JSONDecodeError as e:
            self._send_error(400, "BadRequest", f"invalid JSON: {e}")
            return
        items = body.get("items") if isinstance(body, dict) else None
        if not isinstance(items, list):
            self._send_error(400, "BadRequest",
                             "BindingList body with items required")
            return
        bindings: List[Tuple[str, str, str, str]] = []
        try:
            for it in items:
                if isinstance(it, (tuple, list)):
                    bns, name, uid, node = it
                else:
                    bns = it.get("namespace") or ns or "default"
                    name = it.get("name") or ""
                    uid = it.get("uid") or ""
                    node = (it.get("target") or {}).get("name") \
                        or it.get("nodeName", "")
                bindings.append((bns, name, uid, node))
        except (ValueError, TypeError, AttributeError) as e:
            self._send_error(400, "BadRequest", f"malformed binding: {e}")
            return
        try:
            for bns in {b[0] for b in bindings}:
                self._check_authz("create", "Binding", bns)
        except Forbidden as e:
            self._send_error(403, "Forbidden", str(e))
            return
        if self._reshard_gate_bulk("Pod",
                                   [(b[0], b[1]) for b in bindings]):
            return
        errors = self.server.store.bind_many(bindings)
        failures = [
            {"index": i,
             "code": 404 if isinstance(err, KeyError) else 409,
             "message": str(err)}
            for i, err in enumerate(errors) if err is not None
        ]
        self._send_negotiated(201, {
            "kind": "Status",
            "status": "Success" if not failures else "Failure",
            "bound": len(bindings) - len(failures),
            "failures": failures,
        })

    def _apply_pod_status(self, ns: str, name: str, status: dict,
                          user: str) -> Optional[tuple]:
        """Apply one pods/status payload — the EXACT single-PUT
        semantics (validating admission against the proposed object,
        then phase/podIP/hostIP, nominatedNodeName, conditions in that
        order), shared by the per-object subresource handler and the
        bulk ``/statuses`` verb so both produce identical store mutation
        sequences. Returns None on success, (code, reason, message) on
        failure."""
        store = self.server.store
        # status writes dispatch through validating admission too
        # (NodeRestriction: a kubelet may only write status of pods
        # bound to it). Validators must judge the PROPOSED object —
        # req.obj carries the incoming status applied to a copy of
        # the live pod, old_obj the untouched stored one.
        live = store.get_pod(ns, name)
        if live is not None:
            from kubernetes_tpu.api.types import shallow_copy

            proposed = shallow_copy(live)
            proposed.status = shallow_copy(live.status)
            if status.get("phase"):
                proposed.status.phase = status["phase"]
            if status.get("podIP"):
                proposed.status.pod_ip = status["podIP"]
            if status.get("hostIP"):
                proposed.status.host_ip = status["hostIP"]
            try:
                self.server.admission.validate_only(AdmissionRequest(
                    UPDATE, "Pod", ns, proposed,
                    old_obj=live, user=user, subresource="status",
                ))
            except AdmissionError as e:
                return (422, "Invalid", str(e))
        if live is None:
            return (404, "NotFound", f"pod {name!r} not found")
        if status.get("phase") or status.get("podIP") \
                or status.get("hostIP"):
            store.set_pod_phase(
                ns, name,
                status.get("phase", ""),
                status.get("podIP", ""),
                status.get("hostIP", ""),
            )
        # scheduler-owned status fields (reference pod/status
        # strategy allows conditions + nominatedNodeName through the
        # status subresource — the scheduler's Unschedulable
        # condition and preemption nomination both write here)
        if "nominatedNodeName" in status:
            node = status["nominatedNodeName"]
            if node:
                store.set_nominated_node_name(ns, name, node)
            else:
                store.clear_nominated_node_name(ns, name)
        for cond in status.get("conditions") or ():
            from kubernetes_tpu.api.types import PodCondition

            store.patch_pod_condition(
                ns, name,
                cond if not isinstance(cond, dict)
                else PodCondition(
                    type=cond.get("type", ""),
                    status=cond.get("status", ""),
                    reason=cond.get("reason", ""),
                    message=cond.get("message", ""),
                ))
        return None

    def _bulk_pod_status(self, ns: Optional[str]) -> None:
        """POST .../statuses with a PodStatusList: the bulk hot-path
        verb for status writes — mass-decline condition patches and
        kubelet phase sweeps ship N updates in one request instead of N
        round trips. Each item is its own transaction with the exact
        per-pod semantics of PUT pods/{name}/status
        (``_apply_pod_status``); failures come back positionally."""
        try:
            body = self._read_body()
        except json.JSONDecodeError as e:
            self._send_error(400, "BadRequest", f"invalid JSON: {e}")
            return
        items = body.get("items") if isinstance(body, dict) else None
        if not isinstance(items, list):
            self._send_error(400, "BadRequest",
                             "PodStatusList body with items required")
            return
        try:
            namespaces = {it.get("namespace") or ns or "default"
                          for it in items}
        except AttributeError:
            self._send_error(400, "BadRequest", "malformed status item")
            return
        try:
            user = None
            for item_ns in namespaces:
                user = self._check_authz("update", "pods/status", item_ns)
        except Forbidden as e:
            self._send_error(403, "Forbidden", str(e))
            return
        if user is None:
            user = self._user()
        if self._reshard_gate_bulk("Pod", [
                (it.get("namespace") or ns or "default",
                 it.get("name") or "") for it in items]):
            return
        applied = 0
        failures: List[dict] = []
        for i, it in enumerate(items):
            err = self._apply_pod_status(
                it.get("namespace") or ns or "default",
                it.get("name") or "",
                it.get("status") or {}, user)
            if err is None:
                applied += 1
            else:
                failures.append({"index": i, "code": err[0],
                                 "message": err[2]})
        self._send_negotiated(200, {
            "kind": "Status",
            "status": "Success" if not failures else "Failure",
            "applied": applied,
            "failures": failures,
        })

    def _trace_ingest(self, pods) -> None:
        """Stamp a ``rest.ingest`` instant event for each SAMPLED pod:
        the first hop of a pod's causal trace (REST → queue → solve →
        bind), keyed by pod uid so the scheduler-side spans stitch.
        A bulk request carries ONE propagated context (trace id = the
        batch's elected uid); that explicit inbound decision overrides
        local crc32 for exactly that pod — the rest of the batch keeps
        the deterministic local decision, which the sender made
        identically."""
        tracer = self.server.tracer
        if tracer is None or not tracer.enabled:
            return
        from kubernetes_tpu.observability.tracer import (
            current_request_context)

        ctx = current_request_context()
        parent = tracer.current_span_id()
        for p in pods:
            uid = p.metadata.uid
            if not uid:
                continue
            inbound = ctx.sampled if ctx is not None \
                and ctx.trace == uid else None
            if tracer.sampled(uid, inbound=inbound):
                tracer.event(
                    "rest.ingest", trace=uid, parent_id=parent,
                    pod=f"{p.metadata.namespace}/{p.metadata.name}")

    def _bulk_create(self, kind: str, ns: Optional[str], body: dict,
                     user: str) -> None:
        """POST a {Kind}List to a collection: per-item admission, bulk
        store insert (one lock + one batched watch delivery for pods),
        positional failures. The QPS discipline lives client-side
        (RestClusterClient charges its token bucket per OBJECT, so a
        bulk request is rate-equivalent to N singles)."""
        store = self.server.store
        items = body.get("items")
        if not isinstance(items, list):
            self._send_error(400, "BadRequest", "List body without items")
            return
        failures: List[dict] = []
        decoded: List[tuple] = []    # (orig index, obj)
        for i, item in enumerate(items):
            try:
                # binary bodies carry API objects; JSON carries dicts
                obj = item if not isinstance(item, dict) \
                    else self._decode(item, kind)
                if ns is not None and store.kind_is_namespaced(kind):
                    obj.metadata.namespace = ns
                decoded.append((i, obj))
            except (ValueError, TypeError) as e:
                failures.append({"index": i, "code": 422,
                                 "message": str(e)})
        # topology gate BEFORE admission charges anything: a bulk
        # create touching a frozen or moved slice re-routes wholesale
        if self._reshard_gate_bulk(kind, [
                (o.metadata.namespace, o.metadata.name)
                for _, o in decoded]):
            return
        admitted: List[tuple] = []   # (orig index, AdmissionRequest, obj)
        for i, obj in decoded:
            try:
                req = AdmissionRequest(
                    CREATE, kind, obj.metadata.namespace, obj, user=user)
                obj = self.server.admission.run(req)
                admitted.append((i, req, obj))
            except (ValueError, TypeError, AdmissionError) as e:
                failures.append({"index": i, "code": 422,
                                 "message": str(e)})
        created = 0
        if admitted and kind == "Pod":
            try:
                store.create_pods([obj for _, _, obj in admitted])
                created = len(admitted)
                self._trace_ingest([obj for _, _, obj in admitted])
                admitted = []
            except ValueError:
                # mid-batch duplicate: create_pods inserted nothing
                # (it validates the whole batch first) — fall through
                # to per-item creates so the conflict is attributed
                # and the rest of the batch still lands
                pass
        for i, req, obj in admitted:
            try:
                if kind == "Pod":
                    store.create_pod(obj)
                    self._trace_ingest([obj])
                else:
                    store.create_object(kind, obj)
                created += 1
            except ValueError as e:
                self.server.admission.rollback(req)
                failures.append({"index": i, "code": 409,
                                 "message": str(e)})
        self._send_negotiated(201, {
            "kind": "Status",
            "status": "Success" if not failures else "Failure",
            "created": created,
            "failures": failures,
        })

    def do_POST(self) -> None:
        self._handle_gated(self._do_POST)

    def _do_POST(self) -> None:
        if self._dispatch_admin("POST"):
            return
        if self._reject_if_read_only():
            return
        kind, ns, name, sub, q = self._route()
        if kind == "Lease":
            if sub == "acquire" and name is not None:
                # lease CAS verb (POST .../leases/{name}/acquire): the
                # in-process try_acquire_or_renew, made remote — hollow
                # kubelets' heartbeat leases and leader election over
                # the REST fabric. ``now`` is server-side on purpose:
                # one clock must arbitrate expiry across processes.
                try:
                    self._check_authz("update", "Lease", "")
                except Forbidden as e:
                    self._send_error(403, "Forbidden", str(e))
                    return
                try:
                    body = self._read_body()
                except json.JSONDecodeError as e:
                    self._send_error(400, "BadRequest",
                                     f"invalid JSON: {e}")
                    return
                holder = str(body.get("holder") or "")
                if not holder:
                    self._send_error(400, "BadRequest",
                                     "holder is required")
                    return
                acquired = self.server.store.try_acquire_or_renew(
                    name, holder, time.time(),
                    float(body.get("duration") or 15.0))
                self._send_json(200, {"acquired": bool(acquired),
                                      "holder": holder})
                return
            self._send_error(405, "MethodNotAllowed",
                             "Lease objects are read-only over REST")
            return
        if kind is None:
            path = urlparse(self.path).path.rstrip("/")
            if path.endswith("/bindings"):
                self._bulk_bindings(ns)
                return
            if path.endswith("/statuses"):
                self._bulk_pod_status(ns)
                return
            if path.endswith("/selfsubjectaccessreviews"):
                # virtual kind (reference authorization.k8s.io/v1
                # SelfSubjectAccessReview): any authenticated user may
                # ask "can I?" — the answer comes from the authorizer
                # seam, so it works for allow_all and RBAC alike
                try:
                    body = self._read_body()
                except json.JSONDecodeError as e:
                    self._send_error(400, "BadRequest", f"invalid JSON: {e}")
                    return
                user = self._user()
                attrs = (body.get("spec") or {}).get(
                    "resourceAttributes") or {}
                authz = self.server.authorizer
                if hasattr(authz, "authorize"):
                    allowed = authz.authorize(
                        user, attrs.get("verb", ""),
                        attrs.get("resource", ""),
                        attrs.get("namespace", ""), attrs.get("name", ""),
                    )
                else:
                    allowed = authz(
                        user, attrs.get("verb", ""),
                        attrs.get("resource", ""),
                        attrs.get("namespace", ""),
                    )
                self._send_json(201, {
                    "kind": "SelfSubjectAccessReview",
                    "apiVersion": "v1",
                    "status": {"allowed": bool(allowed)},
                })
                return
            self._send_error(404, "NotFound", f"no route for {self.path}")
            return
        try:
            body = self._read_body()
        except json.JSONDecodeError as e:
            self._send_error(400, "BadRequest", f"invalid JSON: {e}")
            return
        store = self.server.store
        # exec subresource: POST .../pods/{name}/exec with
        # {"container": ..., "command": [...]} — proxied to the owning
        # kubelet like pods/log (reference registry/core/pod/rest/
        # subresources.go ExecREST → kubelet /exec → CRI ExecSync);
        # its own RBAC vocabulary entry, like pods/log
        if kind == "Pod" and sub == "exec" and name is not None:
            try:
                self._check_authz("create", "pods/exec", ns or "")
            except Forbidden as e:
                self._send_error(403, "Forbidden", str(e))
                return
            pod = store.get_pod(ns or "default", name)
            if pod is None:
                self._send_error(404, "NotFound", f"pod {name!r} not found")
                return
            source = store.exec_source(pod.spec.node_name) \
                if pod.spec.node_name else None
            if source is None:
                self._send_error(
                    404, "NotFound",
                    f"no exec source for node {pod.spec.node_name!r} "
                    "(pod not running on a registered kubelet)",
                )
                return
            command = body.get("command") or []
            if not isinstance(command, list) or not command:
                self._send_error(400, "BadRequest",
                                 "a non-empty command list is required")
                return
            try:
                rc, out = source(ns or "default", name,
                                 body.get("container", ""), command)
            except LookupError as e:
                self._send_error(400, "BadRequest", str(e))
                return
            except Exception as e:  # noqa: BLE001 — kubelet-side failure
                self._send_error(500, "InternalError", str(e))
                return
            self._send_json(200, {"kind": "ExecResult",
                                  "exitCode": rc, "output": out})
            return
        # portforward subresource: POST .../pods/{name}/portforward
        # with {"port": N, "data": base64} → one exchange with the
        # owning kubelet's runtime port (reference ExecREST sibling
        # PortForwardREST → kubelet /portForward; the SPDY stream
        # collapses to request/response); own RBAC vocabulary entry
        if kind == "Pod" and sub == "portforward" and name is not None:
            import base64

            try:
                self._check_authz("create", "pods/portforward", ns or "")
            except Forbidden as e:
                self._send_error(403, "Forbidden", str(e))
                return
            pod = store.get_pod(ns or "default", name)
            if pod is None:
                self._send_error(404, "NotFound", f"pod {name!r} not found")
                return
            source = store.portforward_source(pod.spec.node_name) \
                if pod.spec.node_name else None
            if source is None:
                self._send_error(
                    404, "NotFound",
                    f"no portforward source for node "
                    f"{pod.spec.node_name!r}",
                )
                return
            try:
                payload = base64.b64decode(body.get("data", "") or "")
                out = source(ns or "default", name,
                             int(body.get("port") or 0), payload)
            except (LookupError, ValueError) as e:
                self._send_error(400, "BadRequest", str(e))
                return
            except Exception as e:  # noqa: BLE001 — kubelet-side failure
                self._send_error(500, "InternalError", str(e))
                return
            self._send_json(200, {
                "kind": "PortForwardResult",
                "data": base64.b64encode(out).decode(),
            })
            return
        # Binding subresource: POST .../pods/{name}/binding
        if kind == "Pod" and sub == "binding" and name is not None:
            if self._reshard_gate("Pod", ns, name):
                return
            try:
                self._check_authz("create", "Binding", ns or "")
                target = (body.get("target") or {}).get("name") or body.get("nodeName", "")
                store.bind(ns or "default", name, body.get("uid", ""), target)
                self._send_json(201, {"kind": "Status", "status": "Success"})
            except Forbidden as e:
                self._send_error(403, "Forbidden", str(e))
            except KeyError as e:
                self._send_error(404, "NotFound", str(e))
            except ValueError as e:
                self._send_error(409, "Conflict", str(e))
            return
        try:
            user = self._check_authz("create", kind, ns or "")
        except Forbidden as e:
            self._send_error(403, "Forbidden", str(e))
            return
        if name is None and isinstance(body, dict) \
                and body.get("kind") == f"{kind}List":
            self._bulk_create(kind, ns, body, user)
            return
        try:
            # binary bodies carry the API object itself; JSON carries
            # the wire dict
            obj = body if not isinstance(body, dict) \
                else self._decode(body, kind)
        except (ValueError, TypeError) as e:
            # decode failure (bad quantity, wrong shape) is the client's
            # fault — 400, never the store-conflict 409
            self._send_error(400, "BadRequest", str(e))
            return
        adm_req = None
        try:
            if ns is not None and store.kind_is_namespaced(kind):
                obj.metadata.namespace = ns
            if self._reshard_gate(kind, obj.metadata.namespace,
                                  obj.metadata.name):
                return
            if kind == "CertificateSigningRequest":
                # spec.username is the AUTHENTICATED requester, never
                # client-claimed (reference registry/certificates
                # strategy PrepareForCreate) — otherwise any caller
                # could claim a bootstrap identity and mint node certs
                obj.username = user
            adm_req = AdmissionRequest(
                CREATE, kind, obj.metadata.namespace, obj, user=user
            )
            obj = self.server.admission.run(adm_req)
            allocated_ip = None
            if kind == "Service":
                # the registry assigns the VIP (reference
                # pkg/registry/core/service/ipallocator)
                from kubernetes_tpu.proxy.ipallocator import IPAllocatorFull

                try:
                    if obj.cluster_ip:
                        if not self.server.ip_allocator.reserve(obj.cluster_ip):
                            self._send_error(
                                422, "Invalid",
                                f"clusterIP {obj.cluster_ip!r} unavailable",
                            )
                            return
                        allocated_ip = obj.cluster_ip
                    else:
                        allocated_ip = self.server.ip_allocator.allocate()
                        obj.cluster_ip = allocated_ip
                except (IPAllocatorFull, ValueError) as e:
                    # ValueError = malformed IP string — a validation
                    # error, not a store conflict
                    self._send_error(422, "Invalid", str(e))
                    return
            try:
                created = store.create_object(kind, obj)
            except ValueError:
                # don't leak the VIP when the create conflicts
                if allocated_ip is not None:
                    self.server.ip_allocator.release(allocated_ip)
                raise
            if kind == "Pod":
                self._trace_ingest([created])
            self._send_json(201, self._encode(created))
        except AdmissionError as e:
            # admission.run already unwound its own plugins' charges
            self._send_error(422, "Invalid", str(e))
        except ValidationError as e:
            # malformed object (e.g. CRD with no storage version): the
            # client's 422, not a conflict to retry around
            if adm_req is not None:
                self.server.admission.rollback(adm_req)
            self._send_error(422, "Invalid", str(e))
        except ValueError as e:
            # create failed AFTER admission admitted (store conflict):
            # release the quota plugin's in-flight charge immediately
            if adm_req is not None:
                self.server.admission.rollback(adm_req)
            self._send_error(409, "AlreadyExists", str(e))

    def do_PUT(self) -> None:
        self._handle_gated(self._do_PUT)

    def _do_PUT(self) -> None:
        if self._dispatch_admin("PUT"):
            return
        if self._reject_if_read_only():
            return
        kind, ns, name, sub, q = self._route()
        if kind == "Lease":
            self._send_error(405, "MethodNotAllowed",
                             "Lease objects are read-only over REST")
            return
        if kind is None or name is None:
            self._send_error(404, "NotFound", f"no route for {self.path}")
            return
        if self._reshard_gate(kind, ns, name):
            return
        try:
            body = self._read_body()
        except json.JSONDecodeError as e:
            self._send_error(400, "BadRequest", f"invalid JSON: {e}")
            return
        store = self.server.store
        # status subresource — phase/podIP only (kubelet status-manager path)
        if kind == "Pod" and sub == "status":
            try:
                # the subresource is its own authz vocabulary entry
                # (the node role grants "pods/status", not "pods")
                user = self._check_authz("update", "pods/status", ns or "")
            except Forbidden as e:
                self._send_error(403, "Forbidden", str(e))
                return
            err = self._apply_pod_status(ns or "default", name,
                                         body.get("status") or {}, user)
            if err is not None:
                self._send_error(*err)
                return
            self._send_json(200, {"kind": "Status", "status": "Success"})
            return
        try:
            user = self._check_authz("update", kind, ns or "")
        except Forbidden as e:
            self._send_error(403, "Forbidden", str(e))
            return
        try:
            obj = body if not isinstance(body, dict) \
                else self._decode(body, kind)
        except (ValueError, TypeError) as e:
            self._send_error(400, "BadRequest", str(e))
            return
        if obj.metadata.name and obj.metadata.name != name:
            # reference returns 400 when the body renames the URL's object
            self._send_error(
                400, "BadRequest",
                f"name in body ({obj.metadata.name!r}) must match URL ({name!r})",
            )
            return
        obj.metadata.name = name
        try:
            if ns is not None and store.kind_is_namespaced(kind):
                obj.metadata.namespace = ns
            old = store.get_object(kind, obj.metadata.namespace, name)
            if kind == "Service" and old is not None:
                # clusterIP is immutable (reference service strategy
                # ValidateUpdate); an omitted field keeps the assigned VIP
                if not obj.cluster_ip:
                    obj.cluster_ip = old.cluster_ip
                elif obj.cluster_ip != old.cluster_ip:
                    self._send_error(
                        422, "Invalid",
                        f"clusterIP is immutable (have {old.cluster_ip!r})",
                    )
                    return
            obj = self.server.admission.run(
                AdmissionRequest(
                    UPDATE, kind, obj.metadata.namespace, obj, old_obj=old, user=user
                )
            )
            # CAS expectation: the JSON wire carries it in metadata;
            # a binary body IS the object, so its stamped rv serves
            # (body.get on a pickled object would crash the handler)
            if isinstance(body, dict):
                expect = body.get("metadata", {}).get(
                    "resourceVersion") or None
            else:
                expect = obj.metadata.resource_version or None
            updated = store.update_object(kind, obj, expect_rv=expect)
            self._send_json(200, self._encode(updated))
        except AdmissionError as e:
            self._send_error(422, "Invalid", str(e))
        except ConflictError as e:
            self._send_error(409, "Conflict", str(e))
        except KeyError as e:
            self._send_error(404, "NotFound", str(e))

    def do_PATCH(self) -> None:
        self._handle_gated(self._do_PATCH)

    def _do_PATCH(self) -> None:
        """PATCH with RFC 7386 JSON Merge Patch (the default and
        ``application/merge-patch+json``) or RFC 6902 JSON Patch
        (``application/json-patch+json``) — the reference's patch
        handler minus strategic-merge's list-merge keys
        (``apiserver/pkg/endpoints/handlers/patch.go``). The patch
        applies to the WIRE shape of the ROUTE's version, so a
        v1beta1 route patches the nested v1beta1 document."""
        if self._dispatch_admin("PATCH"):
            return
        if self._reject_if_read_only():
            return
        kind, ns, name, sub, q = self._route()
        if kind == "Lease":
            self._send_error(405, "MethodNotAllowed",
                             "Lease objects are read-only over REST")
            return
        if kind is None or name is None or sub is not None:
            self._send_error(404, "NotFound", f"no route for {self.path}")
            return
        try:
            user = self._check_authz("patch", kind, ns or "")
        except Forbidden as e:
            self._send_error(403, "Forbidden", str(e))
            return
        try:
            body = self._read_body()
        except json.JSONDecodeError as e:
            self._send_error(400, "BadRequest", f"invalid JSON: {e}")
            return
        store = self.server.store
        old = store.get_object(kind, ns or "default", name)
        if old is None:
            self._send_error(404, "NotFound", f"{kind} {name!r} not found")
            return
        wire = self._encode(old)
        ctype = (self.headers.get("Content-Type") or "").split(";")[0]
        try:
            if ctype == "application/json-patch+json":
                from kubernetes_tpu.apiserver.webhook import (
                    apply_json_patch,
                )

                if not isinstance(body, list):
                    raise ValueError("a JSON Patch must be an array")
                patched = apply_json_patch(wire, body)
            else:
                patched = json_merge_patch(wire, body)
        except Exception as e:  # noqa: BLE001 — malformed patch
            self._send_error(400, "BadRequest", f"invalid patch: {e}")
            return
        if not isinstance(patched, dict):
            # a scalar merge-patch body replaces the whole document —
            # never a valid API object
            self._send_error(400, "BadRequest",
                             "patch result is not an object")
            return
        # identity is immutable under patch: name, uid, and creation
        # timestamp always come from the stored object (controllers key
        # on uid; a patch must never mint a new one)
        meta = patched.setdefault("metadata", {})
        if not isinstance(meta, dict):
            meta = patched["metadata"] = {}
        meta["name"] = name
        meta["uid"] = old.metadata.uid
        meta["creationTimestamp"] = old.metadata.creation_timestamp
        if kind == "Service" and old.cluster_ip and \
                patched.get("clusterIp") not in (None, old.cluster_ip):
            # same strategy check the PUT path enforces
            self._send_error(
                422, "Invalid",
                f"clusterIP is immutable (have {old.cluster_ip!r})",
            )
            return
        try:
            obj = self._decode(patched, kind)
            if ns is not None and store.kind_is_namespaced(kind):
                obj.metadata.namespace = ns
            obj = self.server.admission.run(AdmissionRequest(
                UPDATE, kind, obj.metadata.namespace, obj, old_obj=old,
                user=user,
            ))
            # CAS on the rv the patch was computed against: a
            # concurrent writer surfaces as 409, like GuaranteedUpdate
            updated = store.update_object(
                kind, obj, expect_rv=old.metadata.resource_version)
            self._send_json(200, self._encode(updated))
        except AdmissionError as e:
            self._send_error(422, "Invalid", str(e))
        except ConflictError as e:
            self._send_error(409, "Conflict", str(e))
        except (ValueError, TypeError) as e:
            self._send_error(400, "BadRequest", str(e))
        except KeyError as e:
            self._send_error(404, "NotFound", str(e))

    def do_DELETE(self) -> None:
        self._handle_gated(self._do_DELETE)

    def _do_DELETE(self) -> None:
        if self._dispatch_admin("DELETE"):
            return
        if self._reject_if_read_only():
            return
        kind, ns, name, sub, q = self._route()
        if kind == "Lease":
            self._send_error(405, "MethodNotAllowed",
                             "Lease objects are read-only over REST")
            return
        if kind is None or name is None:
            self._send_error(404, "NotFound", f"no route for {self.path}")
            return
        if self._reshard_gate(kind, ns, name):
            return
        try:
            self._check_authz("delete", kind, ns or "")
        except Forbidden as e:
            self._send_error(403, "Forbidden", str(e))
            return
        old = self.server.store.get_object(kind, ns or "default", name)
        if old is not None:
            # DELETE dispatches through validating admission (the
            # reference's delete path runs validating plugins/webhooks;
            # there is no body to mutate) — NodeRestriction confines a
            # node identity to deleting its own pods here
            try:
                self.server.admission.validate_only(AdmissionRequest(
                    DELETE, kind, ns or "default", old, old_obj=old,
                    user=self._user(),
                ))
            except AdmissionError as e:
                self._send_error(422, "Invalid", str(e))
                return
        if self.server.store.delete_object(kind, ns or "default", name):
            if kind == "Service" and old is not None and old.cluster_ip:
                self.server.ip_allocator.release(old.cluster_ip)
            self._send_json(200, {"kind": "Status", "status": "Success"})
        else:
            self._send_error(404, "NotFound", f"{kind} {name!r} not found")

    # -- watch streaming ----------------------------------------------
    def _serve_watch(self, kind: str, ns: Optional[str], rv: int,
                     label_sel=None, field_checks=None) -> None:
        binary = self._accepts_binary()
        frames: "queue.Queue[Optional[Any]]" = queue.Queue(maxsize=10_000)
        # capture the REQUEST's api version: the sink runs on store
        # threads, and group-route watches must stream the same wire
        # shape their GETs serve (versioned-codec contract)
        api_version = getattr(self, "_api_version", "v1")
        from kubernetes_tpu.api.scheme import SCHEME_V

        def sink(event_rv: int, event: Event) -> None:
            if event.kind != kind:
                return
            if ns is not None and getattr(event.obj.metadata, "namespace", None) != ns:
                return
            # selector-scoped watch (storage-level filtering; deviation
            # from upstream: an object MODIFIED out of the selector is
            # dropped rather than translated to a synthetic DELETED)
            if label_sel is not None and not label_sel.matches(
                    event.obj.metadata.labels):
                return
            if field_checks is not None and not _field_checks_match(
                    event.obj, field_checks):
                return
            if binary:
                # the Event itself — pickled (once, cached on the event
                # across ALL binary watchers) by the writer thread, so
                # the store's dispatch path never pays an encode under
                # its lock and N watchers never pay N encodes; old_obj
                # rides along because scheduler event handlers key
                # bind/update detection on it (the reference's informers
                # synthesize old from their local cache instead — our
                # binary peers skip that cache)
                frame = event
            else:
                # memoized per event: N watchers must not pay N encodes
                # (reference cachingObject in the watch cache)
                frame = event.__dict__.get("_v1_frame") \
                    if api_version == "v1" else None
                if frame is None:
                    from kubernetes_tpu.api.types import CustomObject

                    wire = _encode_custom(event.obj, api_version) \
                        if isinstance(event.obj, CustomObject) \
                        else SCHEME_V.encode(event.obj, api_version)
                    doc = {"type": event.type, "object": wire}
                    if event.ts:
                        # commit stamp for the freshness SLI (the JSON
                        # wire's analog of the binary 4-tuple)
                        doc["commitTs"] = event.ts
                    frame = json.dumps(doc).encode() + b"\n"
                    if api_version == "v1":
                        event.__dict__["_v1_frame"] = frame
            try:
                frames.put_nowait(frame)
            except queue.Full:
                # slow watcher: drop the connection (apiserver does the
                # same). This sink runs under the store lock, so never
                # block — make room for the close sentinel instead.
                try:
                    frames.get_nowait()
                    frames.put_nowait(None)
                except (queue.Empty, queue.Full):
                    pass

        try:
            handle = self.server.watch_cache.watch_from(rv, sink)
        except TooOldResourceVersion as e:
            self._send_error(410, "Expired", str(e))
            return
        finally:
            # watch-init seats cover exactly the expensive part — the
            # replay/attach burst a reconnect herd multiplies. The
            # stream itself is long-running and must not hold seats
            # (upstream's watch-initialization seat model).
            ticket = self._apf_ticket
            if ticket is not None:
                ticket.release()
        from kubernetes_tpu.apiserver import codec

        self.send_response(200)
        self.send_header(
            "Content-Type",
            codec.BINARY_CONTENT_TYPE if binary else "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self._send_codec_header()
        self.end_headers()
        # the stream's wire contract is pinned for its whole life: a
        # v1-pinned watcher gets legacy 3-tuple frames even though the
        # server's native frame is the 4-tuple (mixed-version roll)
        codec_version = getattr(self, "_codec_version",
                                codec.CODEC_VERSION)
        gate = self.server.fault_gate
        plural = KIND_TO_PLURAL.get(kind, kind.lower() + "s")
        try:
            while not self.server.stopping.is_set():
                if self._sock_aborted:
                    # an injected fault (truncation mid-stream) killed
                    # the socket: writes now land in _DevNullWriter and
                    # never raise, so exit explicitly or this thread
                    # would drain a dead subscription forever
                    break
                if self.server.fenced.is_set():
                    # a read replica that fenced mid-stream must shed
                    # its watchers too: the clean close makes the
                    # client relist — which the fence gate answers with
                    # the re-route 503, landing the stream on a sibling
                    break
                try:
                    frame = frames.get(timeout=0.5)
                except queue.Empty:
                    continue
                if frame is None:
                    break
                if gate is not None and gate._rules:
                    # per-frame watch faults: stalls delay delivery,
                    # drops abort mid-stream with no terminating chunk
                    # (the client must detect the loss and relist)
                    rule = gate.decide("GET", plural, watch=True)
                    if rule is not None:
                        if rule.fault == "watch_stall":
                            time.sleep(rule.duration)
                        elif rule.fault == "watch_drop":
                            self._abort_socket()
                            break
                closing = False
                if binary:
                    # drain the backlog — plus a small flush window so a
                    # steady producer fills the chunk instead of paying
                    # one syscall per event — into ONE length-prefixed
                    # frame: a pickled list of per-event pickles (each
                    # cached on its Event, shared across watchers). The
                    # client hands the whole batch to its handler in one
                    # call (the store's own batched dispatch, kept
                    # batched on the wire; reference streams length-
                    # delimited protobuf).
                    batch = [frame]
                    deadline = None
                    window = self.server.watch_flush_window
                    while len(batch) < 2048:
                        try:
                            nxt = frames.get_nowait()
                        except queue.Empty:
                            if window <= 0.0:
                                break
                            if deadline is None:
                                deadline = time.monotonic() + window
                            left = deadline - time.monotonic()
                            if left <= 0:
                                break
                            try:
                                nxt = frames.get(timeout=left)
                            except queue.Empty:
                                break
                        if nxt is None:
                            closing = True
                            break
                        batch.append(nxt)
                    frame = codec.frame(
                        [_cached_event_bytes(e, codec_version)
                         for e in batch])
                else:
                    # JSON coalescing: several newline-delimited frames
                    # ride one chunk write (readline-based clients parse
                    # them unchanged) — syscalls per batch, not per event
                    parts = [frame]
                    while len(parts) < 512:
                        try:
                            nxt = frames.get_nowait()
                        except queue.Empty:
                            break
                        if nxt is None:
                            closing = True
                            break
                        parts.append(nxt)
                    frame = b"".join(parts)
                self.wfile.write(b"%x\r\n%s\r\n" % (len(frame), frame))
                self.wfile.flush()
                if closing:
                    break
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            handle.stop()
            try:
                self.wfile.write(b"0\r\n\r\n")
            except OSError:
                pass

    # -- read-tier subscription (apiserver/readtier.py) ----------------
    def _serve_subscription(self, u) -> None:
        """The owner's commit stream for read replicas: every watch
        event of every kind, as newline-delimited JSON lines carrying
        {type, kind, rv, object, commitTs}, resumed from
        ``resourceVersion``. Resume sources, in order: the in-memory
        watch cache (replay + live attach under one lock, no seam),
        then the WAL on disk — a restarted owner has an empty cache,
        but its log still holds the window between a replica's cursor
        and the crash, so replicas resubscribe without a full reseed.
        Only when BOTH are compacted past the cursor does the stream
        410 and the replica reseed from ``?snapshot=1``."""
        q = {k: v[0] for k, v in parse_qs(u.query).items()}
        if q.get("snapshot") in ("1", "true"):
            self._serve_subscription_snapshot()
            return
        try:
            rv = int(q.get("resourceVersion") or 0)
        except ValueError:
            self._send_error(
                400, "BadRequest",
                f"invalid resourceVersion {q.get('resourceVersion')!r}")
            return
        frames: "queue.Queue[Optional[bytes]]" = queue.Queue(
            maxsize=50_000)

        def sink(event_rv: int, event: Event) -> None:
            # one encode per event, shared across every subscribed
            # replica (the same cachingObject discipline _serve_watch
            # uses for its JSON frames)
            frame = event.__dict__.get("_sub_frame")
            if frame is None:
                doc = {"type": event.type, "kind": event.kind,
                       "rv": event_rv, "object": to_wire(event.obj)}
                if event.ts:
                    doc["commitTs"] = event.ts
                frame = json.dumps(doc).encode() + b"\n"
                event.__dict__["_sub_frame"] = frame
            try:
                frames.put_nowait(frame)
            except queue.Full:
                # a replica that cannot keep up is cut (it resumes from
                # its cursor — or reseeds — instead of stalling the
                # owner's dispatch)
                try:
                    frames.get_nowait()
                    frames.put_nowait(None)
                except (queue.Empty, queue.Full):
                    pass

        replayed: List[bytes] = []
        handle = None
        try:
            try:
                handle = self.server.watch_cache.watch_from(rv, sink)
            except TooOldResourceVersion:
                handle = self._attach_via_wal(rv, sink, replayed)
            if handle is None:
                self._send_error(
                    410, "Expired",
                    f"resourceVersion {rv} is compacted out of both the "
                    "watch cache and the WAL — reseed from ?snapshot=1")
                return
        finally:
            ticket = self._apf_ticket
            if ticket is not None:
                ticket.release()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        try:
            if replayed:
                body = b"".join(replayed)
                self.wfile.write(b"%x\r\n%s\r\n" % (len(body), body))
                self.wfile.flush()
            while not self.server.stopping.is_set():
                if self._sock_aborted:
                    break
                try:
                    frame = frames.get(timeout=0.5)
                except queue.Empty:
                    continue
                if frame is None:
                    break
                parts = [frame]
                closing = False
                while len(parts) < 512:
                    try:
                        nxt = frames.get_nowait()
                    except queue.Empty:
                        break
                    if nxt is None:
                        closing = True
                        break
                    parts.append(nxt)
                buf = b"".join(parts)
                self.wfile.write(b"%x\r\n%s\r\n" % (len(buf), buf))
                self.wfile.flush()
                if closing:
                    break
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            handle.stop()
            try:
                self.wfile.write(b"0\r\n\r\n")
            except OSError:
                pass

    def _attach_via_wal(self, rv: int, sink, replayed: List[bytes]):
        """WAL fallback for a subscription resume the watch cache can't
        cover: encode the on-disk window (rv, wal-end] into ``replayed``
        frames, then attach the live sink at the replay horizon — any
        event committed while the log was read is newer than the
        horizon and replays from the cache. Returns the live handle, or
        None when the WAL can't prove coverage either (→ 410)."""
        wal_dir = getattr(self.server, "wal_dir", None)
        if not wal_dir:
            return None
        from kubernetes_tpu.apiserver.wal import wal_events_since

        try:
            covered, entries = wal_events_since(wal_dir, rv)
        except OSError:
            return None
        if not covered:
            return None
        top = rv
        for line in entries:
            line_rv = int(line.get("rv") or 0)
            doc: Dict[str, Any] = {"rv": line_rv, "kind": line["k"]}
            if line["t"] == "DEL":
                # key-only delete (the log stores no body): the replica
                # pops its mirrored object and re-announces it at this rv
                doc["type"] = "DELETED"
                doc["key"] = [line.get("ns", ""), line["n"]]
            else:
                doc["type"] = "MODIFIED"
                doc["object"] = line["o"]
            replayed.append(json.dumps(doc).encode() + b"\n")
            top = max(top, line_rv)
        try:
            return self.server.watch_cache.watch_from(top, sink)
        except TooOldResourceVersion:
            return None

    def _serve_subscription_snapshot(self) -> None:
        """Full-state seed for a new (or 410'd) replica: a leading
        {"rv": R} line with R captured BEFORE any kind is listed, then
        per-kind object batches. Events between R and each list are
        delivered again by the subsequent subscription from R — the
        replica's per-object rv guard collapses the overlap, which is
        exactly the adopt_objects idempotency the silent placement
        channel already relies on."""
        store = self.server.store
        rv0 = store.current_rv()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def write_line(doc: dict) -> None:
            body = json.dumps(doc).encode() + b"\n"
            self.wfile.write(b"%x\r\n%s\r\n" % (len(body), body))

        try:
            write_line({"rv": rv0})
            for kind in store.known_kinds():
                if kind == "Lease":
                    # synthesized objects with no watch events — a
                    # mirror of them would never be maintained
                    continue
                try:
                    objs, krv = store.list_objects_with_rv(kind)
                except KeyError:
                    continue
                if not objs:
                    continue
                for i in range(0, len(objs), 500):
                    write_line({
                        "kind": kind, "rv": krv,
                        "objects": [to_wire(o)
                                    for o in objs[i:i + 500]],
                    })
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass

    def _reject_if_read_only(self) -> bool:
        """True when this server is a read replica and the mutating
        request was answered 503: writes belong to the partition owner
        (the client routes them there; this gate catches strays). The
        body is drained first so keep-alive framing survives."""
        if not getattr(self.server, "read_only", False):
            return False
        length = int(self.headers.get("Content-Length") or 0)
        if length:
            self.rfile.read(length)
        body = json.dumps({
            "kind": "Status", "status": "Failure",
            "reason": "ReadOnlyReplica", "code": 503,
            "message": "read replica serves no writes — "
                       "route mutations to the partition owner",
        }).encode()
        self.send_response(503)
        self.send_header("Content-Type", "application/json")
        self.send_header("X-Replica-ReadOnly", "1")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
        return True


class APIServer(ThreadingHTTPServer):
    """In-process kube-apiserver equivalent. Serves a ClusterStore over
    REST; start with .start(), stop with .shutdown_server()."""

    daemon_threads = True
    # an informer herd (re)connects in bursts of hundreds when a
    # replica dies or a topology epoch bumps; socketserver's default
    # backlog of 5 turns that thundering herd into connection-refused
    # churn instead of a queue
    request_queue_size = 512

    def __init__(
        self,
        store: Optional[ClusterStore] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        admission: Optional[AdmissionChain] = None,
        authorizer: Authorizer = allow_all,
        tokens: Optional[Dict[str, str]] = None,
        metrics_text_fn: Optional[Callable[[], str]] = None,
        max_readonly_inflight: Optional[int] = 400,
        max_mutating_inflight: Optional[int] = 200,
        binary_clients: Optional[set] = None,
        fault_gate: Optional[FaultGate] = None,
        watch_flush_window: float = 0.002,
        flow_control: Any = "default",
        partition: Optional[Tuple[int, int]] = None,
        read_only: bool = False,
    ):
        super().__init__((host, port), _Handler)
        # read-tier identity (apiserver/readtier.py): a read replica
        # serves lists/watches from its mirror store and answers every
        # mutating verb 503 — writes belong to the partition owner.
        # ``fenced`` is the replica's staleness circuit breaker: set
        # when replication lag blows the budget, it turns reads into
        # re-route 503s (X-Replica-Fenced) and sheds live watch
        # streams; cleared when the replica catches back up. ``wal_dir``
        # (set by harnesses that attach a WAL) lets the subscription
        # endpoint replay resume windows its in-memory cache lost.
        self.read_only = bool(read_only)
        self.fenced = threading.Event()
        self.wal_dir: Optional[str] = None
        # partitioned-control-plane identity: (index, count) when this
        # server is one shard of a partitioned fabric (its store holds
        # ONLY partition ``index`` of the keyspace — one server process
        # per partition is the sharded-coordinator deployment shape).
        # Served at /api/v1/partitiontopology for client-side sanity
        # checks; (0, 1) = the classic unsharded server.
        self.partition_index, self.partition_count = partition or (0, 1)
        # elastic control plane (live resharding): the runtime topology
        # (None = static PR 9 layout), slices frozen mid-migration
        # (slot -> (deadline, eta)), and the per-slot / per-namespace
        # write ledgers the load-aware rebalancer reads
        self.partition_topology: Optional[Any] = None
        self._topology_lock = threading.Lock()
        self.frozen_slots: Dict[int, Tuple[float, float]] = {}
        self.slot_writes: Dict[int, int] = {}
        self.ns_writes: Dict[str, int] = {}
        # pipelined watch delivery: after the first event of a chunk,
        # wait up to this long for more so a steady producer (informer
        # catch-up, bulk creates) ships hundreds of events per syscall.
        # 0 disables the wait (drain-only coalescing).
        self.watch_flush_window = float(watch_flush_window)
        # pre-encoded list responses (binary, selector-free), validated
        # by the store's per-kind mutation counter: a scheduler relist
        # of 5k nodes while only pods churn costs one cache hit, not a
        # 5k-object pickle
        self._list_cache: Dict[tuple, tuple] = {}
        self._list_cache_lock = threading.Lock()
        # authn/authz LRUs (reference: token cache in front of the
        # authenticator, SubjectAccessReview cache in front of the
        # webhook authorizer): resolved bearer identities and authz
        # decisions, invalidated by the object events that could change
        # them (_maybe_invalidate below)
        self._token_cache: Dict[str, str] = {}
        self._authz_cache: Dict[tuple, bool] = {}
        # chaos middleware: always present (a rule-less gate costs one
        # attribute read per request) so /debug/faults can arm it at
        # runtime without a server restart
        self.fault_gate = fault_gate if fault_gate is not None \
            else FaultGate()
        # flight recorder (observability layer): the process-wide tracer
        # so an in-process scheduler's spans and this server's request
        # spans land in ONE ring — /debug/trace then serves the stitched
        # REST→queue→solve→bind picture
        from kubernetes_tpu.observability import get_tracer

        self.tracer = get_tracer()
        import itertools

        self._req_seq = itertools.count()   # 1-in-N request-span sampling
        # propagated-context observability: how many requests arrived
        # with an X-Ktpu-Trace header (the KTPU_TRACE=off acceptance
        # asserts this stays 0 — the whole layer sheds on the wire)
        self.trace_headers_seen = 0
        # self-protection lanes (reference filters/maxinflight.go
        # defaults: --max-requests-inflight 400,
        # --max-mutating-requests-inflight 200); None = unlimited.
        # Active only when flow_control=None — APF replaces them as the
        # admission decision otherwise, deriving its seat budgets from
        # the same numbers.
        self.readonly_lane = threading.Semaphore(max_readonly_inflight) \
            if max_readonly_inflight else None
        self.mutating_lane = threading.Semaphore(max_mutating_inflight) \
            if max_mutating_inflight else None
        self.lane_stats = {"ro": LaneStats(max_readonly_inflight),
                           "rw": LaneStats(max_mutating_inflight)}
        # API Priority & Fairness (flowcontrol.py, KEP-1040): the
        # default admission path. "default" derives the standard
        # schema/level tiering from the lane budgets; a
        # FlowControlConfig customizes it; None restores the raw lanes.
        if flow_control is None:
            self.flowcontrol: Optional[FlowController] = None
        elif isinstance(flow_control, FlowControlConfig):
            self.flowcontrol = FlowController(flow_control)
        elif isinstance(flow_control, FlowController):
            self.flowcontrol = flow_control
        else:
            self.flowcontrol = FlowController(default_config(
                max_readonly_inflight, max_mutating_inflight))
        # extra non-control-plane identities granted the binary codec
        self.binary_clients = set(binary_clients or ())
        self.store = store if store is not None else ClusterStore()
        self.watch_cache = WatchCache(self.store)
        if admission is None:
            admission = AdmissionChain.default()
            # store-backed plugins: quota gatekeeping charges against
            # live pods; namespace lifecycle rejects creates into
            # Terminating namespaces
            from kubernetes_tpu.apiserver.admission import (
                NamespaceLifecycle,
                ResourceQuotaAdmission,
            )

            from kubernetes_tpu.apiserver.admission import (
                PodPriorityResolver,
            )

            for p in admission.plugins:
                if isinstance(p, NamespaceLifecycle):
                    p.store = self.store
                elif isinstance(p, PodPriorityResolver):
                    # classes resolve from PriorityClass API objects
                    p.store = self.store
            from kubernetes_tpu.apiserver.admission import (
                DefaultStorageClass,
                NodeRestriction,
                ServiceAccountAdmission,
            )

            admission.plugins.append(ServiceAccountAdmission(self.store))
            admission.plugins.append(NodeRestriction())
            admission.plugins.append(DefaultStorageClass(self.store))
            admission.plugins.append(ResourceQuotaAdmission(self.store))
            # out-of-process extension point, last in the chain:
            # mutating webhooks run after the in-process mutators,
            # validating webhooks after every in-process validator
            # (reference mutating-then-validating dispatcher ordering)
            from kubernetes_tpu.apiserver.webhook import WebhookAdmission

            admission.plugins.append(WebhookAdmission(self.store))
        self.admission = admission
        self.authorizer = authorizer
        self.tokens = dict(tokens or {})  # bearer token -> username
        # service-account token index (token -> identity triple), built
        # lazily and invalidated by Secret events. The generation
        # counter closes the rebuild/invalidate race: a rebuild that
        # listed secrets BEFORE a revocation event must not install its
        # snapshot AFTER the event cleared the cache (a revoked token
        # would keep authenticating until an unrelated Secret write).
        self._sa_tokens: Optional[Dict[str, tuple]] = None
        self._sa_gen = 0
        # CSR-issued client-cert index (fingerprint -> CN identity),
        # invalidated by CertificateSigningRequest events the same way
        self._cert_index: Optional[Dict[str, str]] = None
        self._cert_gen = 0

        _AUTHZ_KINDS = frozenset((
            "Role", "ClusterRole", "RoleBinding", "ClusterRoleBinding",
            "CustomResourceDefinition",
        ))

        def _maybe_invalidate(event) -> None:
            if event.kind == "Secret":
                self._sa_gen += 1
                self._sa_tokens = None
                self._token_cache = {}
            elif event.kind == "CertificateSigningRequest":
                self._cert_gen += 1
                self._cert_index = None
                self._token_cache = {}
            elif event.kind == "ServiceAccount":
                # a deleted/recreated account must stop authenticating
                # through the resolved-identity cache immediately (the
                # uid check the uncached path performs per request)
                self._token_cache = {}
            if event.kind in _AUTHZ_KINDS:
                # policy changed: cached allow/deny decisions are void
                # (rebinding the dict is atomic under the GIL — readers
                # see either the old or the fresh empty map)
                self._authz_cache = {}

        self._sa_watch = self.store.watch(_maybe_invalidate)
        self.stopping = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # live client sockets, for hard-kill fidelity in in-proc
        # harnesses: shutdown() only stops the accept loop — pooled
        # keep-alive connections keep being served by their handler
        # threads, so a "killed" in-proc server would stay silently
        # alive to every client that already had a connection
        self._conn_lock = threading.Lock()
        self._live_conns: set = set()
        self._metrics_text_fn = metrics_text_fn
        from kubernetes_tpu.proxy.ipallocator import IPAllocator

        self.ip_allocator = IPAllocator()
        # seed with VIPs of services already in a caller-supplied store so
        # allocate() never hands out an in-use address
        for svc in self.store.list_all_services():
            if svc.cluster_ip:
                self.ip_allocator.reserve(svc.cluster_ip)

    def _sa_token_index(self) -> Dict[str, tuple]:
        """token -> (namespace, sa name, recorded uid), rebuilt lazily
        and invalidated by Secret watch events — authn must not pay an
        O(all secrets) scan per request."""
        idx = self._sa_tokens
        if idx is None:
            from kubernetes_tpu.controllers.serviceaccounttoken import (
                SA_NAME_ANNOTATION,
                SA_TOKEN_TYPE,
                SA_UID_ANNOTATION,
            )

            gen = self._sa_gen
            idx = {}
            for secret in self.store.list_objects("Secret"):
                if secret.type != SA_TOKEN_TYPE:
                    continue
                tok = secret.data.get("token")
                if tok:
                    ann = secret.metadata.annotations
                    idx[tok] = (
                        secret.namespace,
                        ann.get(SA_NAME_ANNOTATION, ""),
                        ann.get(SA_UID_ANNOTATION),
                    )
            if gen == self._sa_gen:
                self._sa_tokens = idx
            # else: a Secret event landed mid-list — serve this
            # request from the snapshot (the request raced the event)
            # but don't cache it
        return idx

    def resolve_sa_token(self, token: str) -> Optional[str]:
        """Map a bearer token to its service-account identity, or None.
        The trust chain: the tokens controller minted the Secret, the
        Secret names its account, and the account must still exist with
        the recorded uid (a recreated same-name account must not be
        impersonable with the old credential — the controller also
        deletes such secrets asynchronously, but authn must not depend
        on that race)."""
        if not token:
            return None
        entry = self._sa_token_index().get(token)
        if entry is None:
            return None
        ns, name, uid = entry
        sa = self.store.get_service_account(ns, name)
        if sa is None or sa.metadata.uid != uid:
            return None
        from kubernetes_tpu.controllers.serviceaccounttoken import (
            sa_username,
        )

        return sa_username(ns, name)

    def _cert_index_map(self) -> Dict[str, str]:
        """sha256(certificate) -> username, rebuilt lazily and
        invalidated by CertificateSigningRequest events (the x509
        authenticator's verified-chain lookup, with the CSR trio as the
        CA). Only client signers participate; the identity is the CN of
        the CSR's subject, exactly kubeadm's TLS-bootstrap contract
        (CN=system:node:<name>, O=system:nodes)."""
        import hashlib

        gen = self._cert_gen
        idx = self._cert_index
        if idx is None:
            from kubernetes_tpu.controllers.certificates import (
                KUBE_APISERVER_CLIENT_KUBELET_SIGNER,
                KUBE_APISERVER_CLIENT_SIGNER,
                sign_request,
            )

            client_signers = (KUBE_APISERVER_CLIENT_KUBELET_SIGNER,
                              KUBE_APISERVER_CLIENT_SIGNER)
            idx = {}
            for csr in self.store.list_objects(
                    "CertificateSigningRequest", None):
                if not csr.certificate or \
                        csr.signer_name not in client_signers:
                    continue
                # only CA-issued bytes authenticate: a forged
                # status.certificate that the signer never produced
                # must not mint an identity
                if csr.certificate != sign_request(csr.request,
                                                   csr.signer_name):
                    continue
                cn = None
                for part in csr.request.split(","):
                    key, _, value = part.strip().partition("=")
                    if key == "CN":
                        cn = value
                        break
                if not cn:
                    continue
                fp = hashlib.sha256(csr.certificate.encode()).hexdigest()
                idx[fp] = cn
            if gen == self._cert_gen:
                self._cert_index = idx
        return idx

    def resolve_cert_fingerprint(self, fingerprint: str) -> Optional[str]:
        if not fingerprint:
            return None
        return self._cert_index_map().get(fingerprint)

    def _cache_token(self, token: str, user: str, cache: Dict) -> None:
        """Insert into the SNAPSHOT of the cache the caller resolved
        against (captured before resolution began): an invalidation
        that raced the resolution rebinds ``_token_cache`` to a fresh
        dict, so the stale identity lands in the discarded one instead
        of resurrecting a just-revoked credential."""
        if len(cache) >= 4096 and cache is self._token_cache:
            self._token_cache = {}
            return
        cache[token] = user

    def authorize_cached(self, user: str, verb: str, kind: str,
                         namespace: str) -> bool:
        """Authz with a decision cache in front: hot-path requests from
        the same identity repeat the same (verb, kind, ns) triple
        thousands of times per second, and the RBAC walk costs a store
        lock + binding scan each time. Invalidated by RBAC/CRD object
        events and by static-group edits (``policy_gen``)."""
        authorizer = self.authorizer
        if authorizer is allow_all:
            return True
        gen = getattr(authorizer, "policy_gen", 0)
        key = (user, verb, kind, namespace, gen)
        cache = self._authz_cache
        hit = cache.get(key)
        if hit is not None:
            return hit
        ok = bool(authorizer(user, verb, kind, namespace))
        # write into the SNAPSHOT captured before the walk: a policy
        # invalidation that raced it rebinds the live dict, and the
        # stale decision must land in the discarded one. On overflow,
        # reset the live dict only if it still IS the snapshot.
        if len(cache) >= 8192 and cache is self._authz_cache:
            self._authz_cache = {}
            return ok
        cache[key] = ok
        return ok

    def cached_list_binary(self, kind: str,
                           namespace: Optional[str]) -> bytes:
        """Pre-encoded binary list response for (kind, namespace),
        validated against the store's per-kind mutation counter: while
        the KIND is unchanged the pickled body is byte-identical, so a
        reflector relist of 5k nodes during pod churn costs a dict hit
        instead of a 5k-object encode. The seq is read BEFORE listing —
        a write racing the encode caches a newer body under an older
        seq, which can only cause a spurious miss, never a stale hit.

        The cached body also carries the rv it listed at: once OTHER
        kinds' churn compacts the watch log past that rv, serving it
        would strand the reflector in a relist→410 loop (its watch from
        the stale rv can never attach) — such entries re-list at the
        current rv instead."""
        from kubernetes_tpu.apiserver import codec

        seq = self.store.kind_seq(kind)
        key = (kind, namespace)
        with self._list_cache_lock:
            hit = self._list_cache.get(key)
        if hit is not None and hit[0] == seq:
            oldest = self.watch_cache.oldest_rv()
            if oldest is None or hit[2] >= oldest - 1:
                return hit[1]
        objs, rv = self.store.list_objects_with_rv(kind, namespace)
        if self.flowcontrol is not None:
            self.flowcontrol.width.note_list_size(
                KIND_TO_PLURAL.get(kind, kind.lower() + "s"), len(objs))
        body = codec.encode(
            {"kind": f"{kind}List", "resourceVersion": rv, "items": objs})
        with self._list_cache_lock:
            if len(self._list_cache) >= 64:
                self._list_cache.clear()
            self._list_cache[key] = (seq, body, rv)
        return body

    def metrics_text(self) -> str:
        if self._metrics_text_fn is not None:
            return self._metrics_text_fn()
        try:
            from kubernetes_tpu.metrics import default_registry

            return default_registry().expose()
        except Exception:
            return ""

    @property
    def port(self) -> int:
        return self.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.server_address[0]}:{self.port}"

    def install_topology(self, topology) -> bool:
        """Install a (newer) live partition topology. Epoch-monotonic:
        a replayed or stale install is refused, so a torn coordinator
        can never roll a server's routing backwards. Installing also
        updates the served partition count and drops frozen slices this
        server no longer owns (their freeze belonged to the migration
        that just committed)."""
        with self._topology_lock:
            cur = self.partition_topology
            if cur is not None and topology.epoch <= cur.epoch:
                return False
            self.partition_topology = topology
            self.partition_count = topology.partitions
            for slot in list(self.frozen_slots):
                if topology.owner[slot] != self.partition_index:
                    self.frozen_slots.pop(slot, None)
            return True

    def invalidate_list_caches(self) -> None:
        """Drop the pre-encoded list cache (adopt/evict bump kind_seq,
        which already invalidates it — this is the belt to that
        suspender for mixed-version callers)."""
        with self._list_cache_lock:
            self._list_cache.clear()

    def start(self) -> "APIServer":
        self._thread = threading.Thread(
            target=self.serve_forever, name="apiserver", daemon=True
        )
        self._thread.start()
        return self

    def handle_error(self, request, client_address):
        # a dropped client connection is normal fabric weather (pool
        # churn, chaos kills, severed keep-alives) — not worth a
        # stderr traceback; anything else keeps the default report
        import sys

        exc = sys.exc_info()[1]
        if isinstance(exc, (ConnectionError, BrokenPipeError)) \
                or self.stopping.is_set():
            return
        super().handle_error(request, client_address)

    def process_request(self, request, client_address):
        with self._conn_lock:
            self._live_conns.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self._conn_lock:
            self._live_conns.discard(request)
        super().shutdown_request(request)

    def sever_connections(self) -> None:
        """Close every live client connection — the in-proc equivalent
        of a SIGKILLed process dropping its sockets. Without this an
        in-proc 'kill' leaves keep-alive clients being served by the
        dead server's surviving handler threads, and chaos cells that
        assert re-route behavior would pass against a zombie."""
        with self._conn_lock:
            conns = list(self._live_conns)
            self._live_conns.clear()
        for s in conns:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    def shutdown_server(self) -> None:
        self.stopping.set()
        self.shutdown()
        self.watch_cache.stop()
        if self._sa_watch is not None:
            self._sa_watch.stop()
        if self._thread is not None:
            self._thread.join(timeout=5)


# ---------------------------------------------------------------------------
# Client (the remote face of client-go's RESTClient + watch package)


class WatchHandle:
    def __init__(self):
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._resp = None

    def stop(self) -> None:
        self._stop.set()
        # Force the blocked readline() to return so the thread, socket,
        # and the server-side sink registration are all released. Must be
        # socket.shutdown, NOT resp.close(): close() needs the buffered-
        # reader lock the blocked readline() holds → deadlock.
        import socket as _socket

        resp = self._resp
        sock = getattr(getattr(resp, "fp", None), "raw", None)
        sock = getattr(sock, "_sock", None)
        if sock is not None:
            try:
                sock.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass


class RestClient:
    """Typed HTTP client. list/watch feed the same informer machinery the
    in-process store feeds (reference client-go RESTClient +
    tools/watch)."""

    def __init__(self, base_url: str, token: str = ""):
        self._crd_plurals: Dict[str, str] = {}
        self.base_url = base_url.rstrip("/")
        self.token = token

    # -- low-level -----------------------------------------------------
    def _request(self, method: str, path: str, body: Any = None,
                 content_type: str = "application/json"):
        import urllib.error
        import urllib.request

        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.base_url + path, data=data, method=method
        )
        req.add_header("Content-Type", content_type)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.status, json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read() or b"{}")

    def _discover_plural(self, kind: str) -> Optional[str]:
        """Resolve a CRD-registered kind's declared plural from the
        server (the reference client's discovery/RESTMapper role):
        naive pluralization would mis-route -y/-s/-x kinds ("Policy" →
        /policys → 404). Cached, including misses (a None entry) so an
        unregistered kind costs ONE discovery round-trip, not one per
        request; the miss cache clears when this client creates a CRD
        (the only registration path it can observe)."""
        if kind in self._crd_plurals:
            return self._crd_plurals[kind]
        code, payload = self._request(
            "GET", "/api/v1/customresourcedefinitions")
        if code == 200:
            for item in payload.get("items", []):
                names = item.get("names") or {}
                if names.get("kind") and names.get("plural"):
                    self._crd_plurals[names["kind"]] = names["plural"]
            self._crd_plurals.setdefault(kind, None)
        return self._crd_plurals.get(kind)

    def _path(self, kind: str, namespace: Optional[str], name: Optional[str] = None,
              sub: Optional[str] = None) -> str:
        plural = KIND_TO_PLURAL.get(kind)
        if plural is None:
            plural = self._discover_plural(kind) or kind.lower() + "s"
        p = f"/api/v1/namespaces/{namespace}/{plural}" if namespace else f"/api/v1/{plural}"
        if name:
            p += f"/{name}"
        if sub:
            p += f"/{sub}"
        return p

    @staticmethod
    def _raise_for(code: int, payload: Any) -> None:
        if code < 400:
            return
        msg = payload.get("message", "") if isinstance(payload, dict) else str(payload)
        if code == 404:
            raise KeyError(msg)
        if code == 409:
            raise ConflictError(msg)
        if code in (403, 422):
            raise PermissionError(msg)
        raise RuntimeError(f"HTTP {code}: {msg}")

    # -- typed verbs ---------------------------------------------------
    @staticmethod
    def _kind_name(obj) -> str:
        # CustomObject instances carry their runtime-registered kind
        return getattr(obj, "kind", None) if type(obj).__name__ == \
            "CustomObject" else type(obj).__name__

    def create(self, obj) -> Any:
        kind = self._kind_name(obj)
        if kind == "CustomResourceDefinition":
            # a fresh registration obsoletes cached discovery misses
            self._crd_plurals = {
                k: v for k, v in self._crd_plurals.items() if v
            }
        ns = obj.metadata.namespace if is_namespaced(kind) else None
        code, payload = self._request(
            "POST", self._path(kind, ns), to_wire(obj)
        )
        self._raise_for(code, payload)
        return from_wire(payload, kind)

    def get(self, kind: str, name: str, namespace: Optional[str] = "default"):
        ns = namespace if is_namespaced(kind) else None
        code, payload = self._request("GET", self._path(kind, ns, name))
        if code == 404:
            return None
        self._raise_for(code, payload)
        return from_wire(payload, kind)

    def list(self, kind: str, namespace: Optional[str] = None,
             label_selector: str = "",
             field_selector: str = "") -> Tuple[List[Any], int]:
        """→ (objects, listResourceVersion) for watch bootstrapping.
        Selectors filter SERVER-side (?labelSelector= / ?fieldSelector=),
        like client-go ListOptions."""
        from urllib.parse import urlencode

        path = self._path(kind, namespace)
        params = {}
        if label_selector:
            params["labelSelector"] = label_selector
        if field_selector:
            params["fieldSelector"] = field_selector
        if params:
            path += "?" + urlencode(params)
        code, payload = self._request("GET", path)
        self._raise_for(code, payload)
        rv = int(payload.get("metadata", {}).get("resourceVersion") or 0)
        return [from_wire(item, kind) for item in payload.get("items", [])], rv

    def update(self, obj) -> Any:
        kind = self._kind_name(obj)
        ns = obj.metadata.namespace if is_namespaced(kind) else None
        code, payload = self._request(
            "PUT", self._path(kind, ns, obj.metadata.name), to_wire(obj)
        )
        self._raise_for(code, payload)
        return from_wire(payload, kind)

    def delete(self, kind: str, name: str, namespace: Optional[str] = "default") -> bool:
        """True = deleted, False = not found; authorization and
        admission failures raise (a 403/422 must never read as a
        routine miss)."""
        ns = namespace if is_namespaced(kind) else None
        code, payload = self._request("DELETE", self._path(kind, ns, name))
        if code in (403, 422):
            self._raise_for(code, payload)
        return code == 200

    def patch(self, kind: str, name: str, patch: Any,
              namespace: Optional[str] = "default",
              patch_type: str = "merge") -> Any:
        """PATCH with merge (RFC 7386, default) or json (RFC 6902)
        semantics."""
        ns = namespace if is_namespaced(kind) else None
        ctype = ("application/json-patch+json" if patch_type == "json"
                 else "application/merge-patch+json")
        code, payload = self._request(
            "PATCH", self._path(kind, ns, name), patch,
            content_type=ctype,
        )
        self._raise_for(code, payload)
        return from_wire(payload, kind)

    def pod_exec(self, namespace: str, name: str, container: str,
                 command: List[str]) -> Tuple[int, str]:
        """POST pods/{name}/exec → (exit code, output) from the owning
        kubelet's runtime (reference kubectl exec → ExecREST → kubelet
        /exec)."""
        code, payload = self._request(
            "POST", self._path("Pod", namespace, name, "exec"),
            {"container": container, "command": list(command)},
        )
        self._raise_for(code, payload)
        return payload.get("exitCode", 1), payload.get("output", "")

    def pod_logs(self, namespace: str, name: str,
                 container: str = "") -> str:
        """GET pods/{name}/log (text/plain, unlike the JSON verbs)."""
        import urllib.request
        from urllib.parse import quote

        path = self._path("Pod", namespace, name, "log")
        if container:
            path += f"?container={quote(container)}"
        req = urllib.request.Request(self.base_url + path)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.read().decode()
        except urllib.error.HTTPError as e:
            payload = e.read()
            try:
                msg = json.loads(payload or b"{}").get("message", "")
            except json.JSONDecodeError:
                msg = payload.decode(errors="replace")
            self._raise_for(e.code, {"message": msg})
            raise

    def bind(self, namespace: str, name: str, uid: str, node_name: str) -> None:
        code, payload = self._request(
            "POST",
            self._path("Pod", namespace, name, "binding"),
            {"kind": "Binding", "target": {"name": node_name}, "uid": uid},
        )
        self._raise_for(code, payload)

    def update_pod_status(self, namespace: str, name: str, phase: str,
                          pod_ip: str = "", host_ip: str = "") -> None:
        code, payload = self._request(
            "PUT",
            self._path("Pod", namespace, name, "status"),
            {"status": {"phase": phase, "podIP": pod_ip, "hostIP": host_ip}},
        )
        self._raise_for(code, payload)

    def can_i(self, verb: str, resource: str, namespace: str = "",
              name: str = "") -> bool:
        """SelfSubjectAccessReview: ask the server whether the caller's
        token may perform verb on resource (authorization.k8s.io)."""
        code, payload = self._request(
            "POST", "/api/v1/selfsubjectaccessreviews",
            {
                "kind": "SelfSubjectAccessReview",
                "spec": {"resourceAttributes": {
                    "verb": verb, "resource": resource,
                    "namespace": namespace, "name": name,
                }},
            },
        )
        self._raise_for(code, payload)
        return bool((payload.get("status") or {}).get("allowed"))

    def healthz(self) -> bool:
        import urllib.request

        try:
            with urllib.request.urlopen(self.base_url + "/healthz", timeout=5) as r:
                return r.status == 200
        except Exception:
            return False

    # -- watch ---------------------------------------------------------
    def watch(
        self,
        kind: str,
        resource_version: int,
        fn: Callable[[str, Any], None],
        namespace: Optional[str] = None,
        on_expired: Optional[Callable[[], None]] = None,
    ) -> WatchHandle:
        """Stream watch events; fn(type, obj) per frame on a daemon
        thread. On HTTP 410 (compacted RV) calls on_expired and exits —
        the reflector's relist trigger."""
        import urllib.error
        import urllib.request

        handle = WatchHandle()
        path = self._path(kind, namespace) + f"?watch=true&resourceVersion={resource_version}"
        req = urllib.request.Request(self.base_url + path)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")

        def run() -> None:
            try:
                resp = urllib.request.urlopen(req)
            except urllib.error.HTTPError as e:
                if e.code == 410 and on_expired is not None:
                    on_expired()
                return
            handle._resp = resp
            if handle._stop.is_set():
                resp.close()
                return
            with resp:
                try:
                    while not handle._stop.is_set():
                        line = resp.readline()
                        if not line:
                            break
                        line = line.strip()
                        if not line:
                            continue
                        frame = json.loads(line)
                        fn(frame["type"], from_wire(frame["object"], kind))
                except (OSError, ValueError, json.JSONDecodeError):
                    # connection closed (possibly mid-frame) by stop()
                    pass

        handle._thread = threading.Thread(target=run, daemon=True, name=f"watch-{kind}")
        handle._thread.start()
        return handle
