"""In-process cluster state store with watches and the Binding subresource.

Plays the role the reference's apiserver+etcd+client-go stack plays for the
scheduler: a typed object store with monotonic resource versions, watch
event fan-out (the informer feed — reference
``tools/cache/reflector.go:254`` ListAndWatch → DeltaFIFO → handlers), the
pod **Binding** subresource (``pkg/registry/core/pod/storage/storage.go:159``
— setting ``spec.nodeName`` transactionally), and the lister surface
plugins consume. ``scheduler_perf`` semantics carry over: there are no
kubelets; a bound pod is a finished pod (SURVEY.md section 3.5).

Thread-safety: all mutations take the store lock; watch events are
dispatched synchronously in order (the in-process equivalent of the
watch-cache fan-out), so handler ordering matches event ordering.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import (
    CSINode,
    ClusterRole,
    ClusterRoleBinding,
    DaemonSet,
    shallow_copy,
    Deployment,
    Endpoints,
    CronJob,
    EndpointSlice,
    Event as ApiEvent,
    HorizontalPodAutoscaler,
    Job,
    Namespace,
    Node,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    PodDisruptionBudget,
    ReplicaSet,
    ReplicationController,
    ResourceQuota,
    Role,
    RoleBinding,
    Service,
    ServiceAccount,
    StatefulSet,
    StorageClass,
)

ADDED, MODIFIED, DELETED = "ADDED", "MODIFIED", "DELETED"


class ValidationError(ValueError):
    """A malformed object (e.g. a CRD version list with no storage
    version) — the client's 422, never the conflict 409 that plain
    ValueError means on the create path."""


class ConflictError(Exception):
    """resourceVersion precondition failed (HTTP 409; reference
    apierrors.NewConflict from GuaranteedUpdate)."""


@dataclass
class Event:
    type: str
    kind: str
    obj: Any
    old_obj: Any = None
    # store-commit wall-clock timestamp, stamped ONCE at dispatch time
    # (the freshness SLI layer's anchor: watch delivery and snapshot
    # staleness are both measured against it). 0.0 = synthetic event
    # (informer initial-sync replay, relist diff) — not measured.
    ts: float = 0.0
    # commit-time origin trace context (the X-Ktpu-Trace header value
    # of the request whose commit produced this event, when that
    # request carried a SAMPLED context) — rides the cached binary
    # watch frame so a watcher can stitch delivery back to the
    # originating trace across the process boundary. None = untraced.
    origin: Any = None


def _commit_origin():
    """The sampled inbound trace context of the request committing on
    this thread (rest.py sets it per request), serialized to its wire
    form — or None. Read once per dispatch batch."""
    from kubernetes_tpu.observability.tracer import (
        current_request_context,
    )

    ctx = current_request_context()
    if ctx is not None and ctx.sampled:
        return ctx.header_value()
    return None


class WatchHandle:
    def __init__(self, store: "ClusterStore", fn: Callable[[Event], None],
                 batch_fn: Optional[Callable[[List[Event]], None]] = None):
        self._store = store
        self.fn = fn
        # optional bulk delivery: a watcher that can absorb a whole
        # event batch under one of ITS locks registers batch_fn; the
        # store's bulk mutators then deliver one call instead of N
        self.batch_fn = batch_fn

    def stop(self) -> None:
        self._store._remove_watch(self)


class _Lease:
    __slots__ = ("holder", "renew_time", "duration")

    def __init__(self, holder: str, renew_time: float, duration: float):
        self.holder = holder
        self.renew_time = renew_time
        self.duration = duration


class ClusterStore:
    def __init__(self, rv_source: Optional[Callable[[], int]] = None):
        from kubernetes_tpu.metrics.freshness_metrics import (
            freshness_metrics,
        )

        self._lock = threading.RLock()
        self._rv = 0
        # optional shared resourceVersion allocator (the partitioned
        # store hands every partition the same atomic counter so RVs
        # stay globally unique and comparable across partitions; None =
        # this store owns its own sequence, exactly as before)
        self._rv_source = rv_source
        # commit-time event stamping rides the freshness toggle with
        # the rest of the SLI layer: the ``freshab`` on/off A/B (and
        # ``KTPU_FRESHNESS=off``) must shed the stamping cost too, not
        # just the downstream observation
        self._freshness = freshness_metrics()
        self._pods: Dict[str, Pod] = {}           # "ns/name" -> Pod
        self._nodes: Dict[str, Node] = {}
        self._services: Dict[str, Service] = {}
        self._rcs: Dict[str, ReplicationController] = {}
        self._rss: Dict[str, ReplicaSet] = {}
        self._sss: Dict[str, StatefulSet] = {}
        self._pvcs: Dict[str, PersistentVolumeClaim] = {}
        self._pvs: Dict[str, PersistentVolume] = {}
        self._storage_classes: Dict[str, StorageClass] = {}
        self._csi_nodes: Dict[str, CSINode] = {}
        self._pdbs: Dict[str, PodDisruptionBudget] = {}
        self._roles: Dict[str, Role] = {}
        self._cluster_roles: Dict[str, ClusterRole] = {}
        self._role_bindings: Dict[str, RoleBinding] = {}
        self._cluster_role_bindings: Dict[str, ClusterRoleBinding] = {}
        # admission webhook registrations (admissionregistration.k8s.io)
        self._mutating_webhooks: Dict[str, Any] = {}
        self._validating_webhooks: Dict[str, Any] = {}
        self._secrets: Dict[str, Any] = {}
        self._priority_classes: Dict[str, Any] = {}
        self._config_maps: Dict[str, Any] = {}
        self._csrs: Dict[str, Any] = {}
        # CRD analog (apiextensions-apiserver): the CRD objects plus
        # per-instance storage for runtime-registered kinds
        self._crds: Dict[str, Any] = {}
        self._custom_kinds: Dict[str, Tuple[Dict[str, Any], bool]] = {}
        self._custom_plurals: Dict[str, str] = {}
        # kind -> (group, served version names) for group-route serving
        self._custom_served: Dict[str, Tuple[str, tuple]] = {}
        self._endpoints: Dict[str, Endpoints] = {}
        self._deployments: Dict[str, Deployment] = {}
        self._daemon_sets: Dict[str, DaemonSet] = {}
        self._jobs: Dict[str, Job] = {}
        self._namespaces: Dict[str, Namespace] = {}
        self._quotas: Dict[str, ResourceQuota] = {}
        self._service_accounts: Dict[str, ServiceAccount] = {}
        self._cron_jobs: Dict[str, CronJob] = {}
        self._hpas: Dict[str, HorizontalPodAutoscaler] = {}
        self._endpoint_slices: Dict[str, EndpointSlice] = {}
        self._leases: Dict[str, _Lease] = {}
        self._api_events: Dict[str, ApiEvent] = {}
        # Event objects expire (reference: etcd lease TTL on events,
        # --event-ttl=1h on the apiserver)
        self.event_ttl = 3600.0
        self._watches: List[WatchHandle] = []
        self._assumed_pvs: Dict[str, str] = {}  # pv name -> pvc key (Reserve)
        # node name -> log provider fn(ns, name, container) -> str: the
        # in-process analog of the apiserver->kubelet log proxy
        # connection (pods/log subresource); kubelets register on start
        self._log_sources: Dict[str, Callable] = {}
        self._exec_sources: Dict[str, Callable] = {}
        self._portforward_sources: Dict[str, Callable] = {}
        # per-kind mutation counters (bumped alongside every dispatch and
        # by the dispatch-free status patches): lets the REST layer serve
        # a pre-encoded list response while the KIND is unchanged — the
        # global _rv advances on every write of any kind, so it cannot
        # validate a per-kind cache
        self._kind_seq: Dict[str, int] = {}
        # silent mutation sinks (``watch_silent``): observers of the
        # adopt/evict channel the live resharding machinery uses to move
        # objects between partitions WITHOUT watch events (the objects
        # did not change — only their placement did). The WAL subscribes
        # here so a migrated object survives a partition failover.
        self._silent_sinks: List[Callable[[List[Event]], None]] = []

    # ------------------------------------------------------------------
    def _next_rv(self) -> str:
        if self._rv_source is not None:
            # allocated from the shared counter, but remembered locally:
            # current_rv()/list RVs stay "the newest revision THIS
            # store committed" (the per-partition cursor component)
            self._rv = self._rv_source()
        else:
            self._rv += 1
        return str(self._rv)

    def kind_seq(self, kind: str) -> int:
        """Mutation counter for one kind (REST list-cache validation)."""
        with self._lock:
            return self._kind_seq.get(kind, 0)

    def _bump_kind(self, kind: str) -> None:
        self._kind_seq[kind] = self._kind_seq.get(kind, 0) + 1

    def _dispatch(self, event: Event) -> None:
        self._bump_kind(event.kind)
        if not event.ts and self._freshness.enabled:
            event.ts = time.time()
        if event.origin is None:
            event.origin = _commit_origin()
        for w in list(self._watches):
            w.fn(event)

    def _dispatch_many(self, events: List[Event]) -> None:
        """Deliver a batch of events, preserving per-watcher ordering.
        Watchers that registered a batch_fn get ONE call (they fan the
        batch out under a single lock acquisition on their side); plain
        watchers see the same events one by one."""
        if not events:
            return
        # commit-time stamp, once per batch (the freshness SLI anchor
        # + the origin trace context of the committing request)
        origin = _commit_origin()
        if self._freshness.enabled:
            now = time.time()
            for e in events:
                self._bump_kind(e.kind)
                if not e.ts:
                    e.ts = now
                if e.origin is None:
                    e.origin = origin
        else:
            for e in events:
                self._bump_kind(e.kind)
                if e.origin is None:
                    e.origin = origin
        for w in list(self._watches):
            if w.batch_fn is not None:
                w.batch_fn(events)
            else:
                for e in events:
                    w.fn(e)

    def watch(self, fn: Callable[[Event], None],
              batch_fn: Optional[Callable[[List[Event]], None]] = None
              ) -> WatchHandle:
        with self._lock:
            h = WatchHandle(self, fn, batch_fn)
            self._watches.append(h)
            return h

    def _remove_watch(self, handle: WatchHandle) -> None:
        with self._lock:
            if handle in self._watches:
                self._watches.remove(handle)

    # ------------------------------------------------------------------
    # silent placement channel (live partition resharding)
    def watch_silent(self, batch_fn: Callable[[List[Event]], None]):
        """Observe SILENT mutations (``adopt_objects``/``evict_objects``)
        — placement moves that must reach durability (the WAL) but must
        NOT reach watchers: the object didn't change, only which
        partition holds it, and a watch event here would double-deliver
        state every consumer already has. Returns a stop() handle."""
        with self._lock:
            self._silent_sinks.append(batch_fn)

        class _SilentHandle:
            def __init__(self, store, fn):
                self._store, self._fn = store, fn

            def stop(self) -> None:
                with self._store._lock:
                    if self._fn in self._store._silent_sinks:
                        self._store._silent_sinks.remove(self._fn)

        return _SilentHandle(self, batch_fn)

    def _dispatch_silent(self, events: List[Event]) -> None:
        for e in events:
            # the pre-encoded REST list cache keys on kind_seq — an
            # adopted object MUST invalidate it even though no watcher
            # hears about the move
            self._bump_kind(e.kind)
        for fn in list(self._silent_sinks):
            fn(events)

    def adopt_objects(self, kind: str, objs: List[Any]) -> int:
        """Insert objects PRESERVING their resourceVersions and firing
        no watch events — the receiving half of a live slice migration
        (the source partition committed these revisions; re-stamping or
        re-announcing them would duplicate history). Existing entries
        are only overwritten by an equal-or-newer revision (a late
        retry must never regress a post-migration write). Returns the
        number adopted."""
        events: List[Event] = []
        with self._lock:
            for obj in objs:
                table, key = self._table_key(
                    kind, obj.metadata.namespace, obj.metadata.name)
                try:
                    rv = int(obj.metadata.resource_version or 0)
                except (TypeError, ValueError):
                    rv = 0
                cur = table.get(key)
                if cur is not None:
                    try:
                        if int(cur.metadata.resource_version or 0) > rv:
                            continue
                    except (TypeError, ValueError):
                        pass
                table[key] = obj
                # the etcd-restore rule, applied across the shard seam:
                # this store's future revisions must exceed every
                # revision it adopted, or per-object RV monotonicity —
                # which every watch consumer and the client's handoff
                # filter depend on — would break at the migration
                self._rv = max(self._rv, rv)
                events.append(Event(MODIFIED, kind, obj))
            self._dispatch_silent(events)
        return len(events)

    def apply_replicated(self, events: List[Event]) -> List[Event]:
        """RV-preserving apply of a replicated event batch — the read
        tier's mirror ingest (apiserver/readtier.py). Like
        ``adopt_objects`` it never re-stamps resourceVersions (the
        owner committed them); unlike it, applied events ARE dispatched
        to this store's watchers — a replica's watch clients must see
        the owner's history verbatim, commit stamps included. The
        per-object equal-rv/newer guard collapses subscription resume
        overlap (a replayed event the mirror already holds is dropped,
        never re-announced), so the replica's watch log stays exactly
        as duplicate-free as the owner's. Returns the applied events.

        DELETED events may carry a key-only stub (a WAL-replayed
        delete has no object body); the mirrored object is popped and
        re-announced at the event's rv so the replica's watch history
        stays rv-monotonic like the owner's."""
        applied: List[Event] = []
        with self._lock:
            for e in events:
                try:
                    table, key = self._table_key(
                        e.kind, getattr(e.obj.metadata, "namespace", ""),
                        e.obj.metadata.name)
                except KeyError:
                    continue   # kind this mirror doesn't know
                try:
                    rv = int(e.obj.metadata.resource_version or 0)
                except (TypeError, ValueError):
                    rv = 0
                cur = table.get(key)
                cur_rv = 0
                if cur is not None:
                    try:
                        cur_rv = int(cur.metadata.resource_version or 0)
                    except (TypeError, ValueError):
                        cur_rv = 0
                if e.type == DELETED:
                    if cur is None or cur_rv > rv:
                        continue
                    table.pop(key, None)
                    # announce the STORED object (a key-only WAL stub
                    # has no body), stamped at the delete's revision
                    cur.metadata.resource_version = str(rv)
                    e = Event(DELETED, e.kind, cur, ts=e.ts,
                              origin=e.origin)
                else:
                    if cur is not None and cur_rv >= rv:
                        continue
                    table[key] = e.obj
                self._rv = max(self._rv, rv)
                applied.append(e)
            self._dispatch_many(applied)
        return applied

    def evict_objects(self, kind: str,
                      keys: List[Tuple[str, str]]) -> List[Any]:
        """Remove objects silently — the source half of a live slice
        migration (the object lives on in its new partition, so a
        DELETED event here would be a lie every watcher acts on).
        Returns the evicted objects."""
        events: List[Event] = []
        out: List[Any] = []
        with self._lock:
            for namespace, name in keys:
                table, key = self._table_key(kind, namespace, name)
                obj = table.pop(key, None)
                if obj is not None:
                    out.append(obj)
                    events.append(Event(DELETED, kind, obj))
            self._dispatch_silent(events)
        return out

    # ------------------------------------------------------------------
    # pods
    def create_pod(self, pod: Pod) -> Pod:
        with self._lock:
            key = pod.full_name()
            if key in self._pods:
                raise ValueError(f"pod {key} already exists")
            if not pod.metadata.creation_timestamp:
                pod.metadata.creation_timestamp = time.time()
            pod.metadata.resource_version = self._next_rv()
            self._pods[key] = pod
            self._dispatch(Event(ADDED, "Pod", pod))
            return pod

    def create_pods(self, pods: List[Pod]) -> List[Pod]:
        """Bulk pod admission: one lock acquisition and one batched watch
        delivery for N creates. Each pod still gets its own resource
        version and its own ADDED event — only the locking/dispatch
        overhead is amortized (the 5000-QPS per-request discipline of the
        reference harness, `util.go:63-68`, is an artifact of its HTTP
        client, not a semantic requirement)."""
        events: List[Event] = []
        with self._lock:
            # validate the whole batch before mutating anything: a mid-
            # batch duplicate must not leave inserted-but-never-announced
            # pods behind (watchers see all of the batch or none of it)
            seen = set()
            for pod in pods:
                key = pod.full_name()
                if key in self._pods or key in seen:
                    raise ValueError(f"pod {key} already exists")
                seen.add(key)
            now = time.time()
            for pod in pods:
                if not pod.metadata.creation_timestamp:
                    pod.metadata.creation_timestamp = now
                pod.metadata.resource_version = self._next_rv()
                self._pods[pod.full_name()] = pod
                events.append(Event(ADDED, "Pod", pod))
            self._dispatch_many(events)
        return pods

    def bind_many(
        self, bindings: List[Tuple[str, str, str, str]]
    ) -> List[Optional[Exception]]:
        """Bulk Binding subresource: one lock + one batched watch delivery
        for N (namespace, name, uid, node_name) bindings. Per-pod failures
        (missing pod, uid mismatch, already bound) are returned
        positionally instead of aborting the batch — each binding is its
        own transaction, exactly as N sequential ``bind`` calls."""
        errors: List[Optional[Exception]] = [None] * len(bindings)
        events: List[Event] = []
        with self._lock:
            for i, (namespace, name, uid, node_name) in enumerate(bindings):
                key = f"{namespace}/{name}"
                pod = self._pods.get(key)
                if pod is None:
                    errors[i] = KeyError(f"pod {key} not found")
                    continue
                if uid and pod.uid != uid:
                    errors[i] = ValueError(f"pod {key} uid mismatch")
                    continue
                if pod.spec.node_name and pod.spec.node_name != node_name:
                    errors[i] = ValueError(
                        f"pod {key} is already assigned to node "
                        f"{pod.spec.node_name!r}")
                    continue
                new_pod = shallow_copy(pod)
                new_pod.spec = shallow_copy(pod.spec)
                new_pod.spec.node_name = node_name
                new_pod.metadata = shallow_copy(pod.metadata)
                new_pod.metadata.resource_version = self._next_rv()
                self._pods[key] = new_pod
                events.append(Event(MODIFIED, "Pod", new_pod, pod))
            self._dispatch_many(events)
        return errors

    def update_pod(self, pod: Pod) -> Pod:
        with self._lock:
            key = pod.full_name()
            old = self._pods.get(key)
            if old is None:
                raise KeyError(f"pod {key} not found")
            pod.metadata.resource_version = self._next_rv()
            self._pods[key] = pod
            self._dispatch(Event(MODIFIED, "Pod", pod, old))
            return pod

    def delete_pod(self, namespace: str, name: str) -> None:
        self._delete(self._pods, "Pod", f"{namespace}/{name}")

    def delete_pods(self, keys: List[Tuple[str, str]]) -> None:
        """Bulk delete ((namespace, name) pairs): one lock acquisition
        AND one batched watch delivery — the mass-preemption path evicts
        thousands of victims per batch, and per-event delivery would
        cost a queue move-all per victim (same rationale as
        ``create_pods``/``bind_pods``). Finalizer-carrying pods keep the
        single-delete marking semantics."""
        events: List[Event] = []
        with self._lock:
            for namespace, name in keys:
                key = f"{namespace}/{name}"
                old = self._pods.get(key)
                if old is None:
                    continue
                if old.metadata.finalizers:
                    self._delete(self._pods, "Pod", key)
                    continue
                self._pods.pop(key)
                events.append(Event(DELETED, "Pod",
                                    self._deletion_copy(old)))
            self._dispatch_many(events)

    def get_pod(self, namespace: str, name: str) -> Optional[Pod]:
        with self._lock:
            return self._pods.get(f"{namespace}/{name}")

    def list_pods(self, namespace: Optional[str] = None) -> List[Pod]:
        with self._lock:
            if namespace is None:
                return list(self._pods.values())
            return [p for p in self._pods.values() if p.namespace == namespace]

    def bind(self, namespace: str, name: str, uid: str, node_name: str) -> None:
        """The Binding subresource (storage.go:159 BindingREST.Create →
        setPodHostAndAnnotations): transactionally sets spec.nodeName on the
        live object, failing on UID mismatch or an already-bound pod."""
        with self._lock:
            key = f"{namespace}/{name}"
            pod = self._pods.get(key)
            if pod is None:
                raise KeyError(f"pod {key} not found")
            if uid and pod.uid != uid:
                raise ValueError(f"pod {key} uid mismatch")
            if pod.spec.node_name and pod.spec.node_name != node_name:
                raise ValueError(f"pod {key} is already assigned to node "
                                 f"{pod.spec.node_name!r}")
            # build a fresh object so watchers' `old` stays unassigned
            # (in-process stores have no serialization boundary to copy for us)
            new_pod = shallow_copy(pod)
            new_pod.spec = shallow_copy(pod.spec)
            new_pod.spec.node_name = node_name
            new_pod.metadata = shallow_copy(pod.metadata)
            new_pod.metadata.resource_version = self._next_rv()
            self._pods[key] = new_pod
            self._dispatch(Event(MODIFIED, "Pod", new_pod, pod))

    def patch_pod_condition(self, namespace: str, name: str, condition) -> None:
        with self._lock:
            pod = self._pods.get(f"{namespace}/{name}")
            if pod is None:
                return
            pod.status.conditions = [
                c for c in pod.status.conditions if c.type != condition.type
            ] + [condition]
            # no event, but the object DID change: the REST layer's
            # pre-encoded list cache must not serve the old conditions
            self._bump_kind("Pod")

    def set_nominated_node_name(self, namespace: str, name: str, node: str) -> None:
        with self._lock:
            pod = self._pods.get(f"{namespace}/{name}")
            if pod is not None:
                pod.status.nominated_node_name = node
                self._bump_kind("Pod")

    def clear_nominated_node_name(self, namespace: str, name: str) -> None:
        self.set_nominated_node_name(namespace, name, "")

    def batched_status_writes(self):
        """No-op scope for the in-process store (API parity with
        ``RestClusterClient.batched_status_writes``): store calls are
        already one lock acquisition each, there are no round trips to
        collapse."""
        import contextlib

        return contextlib.nullcontext()

    # ------------------------------------------------------------------
    # generic add/update/delete for the remaining kinds
    def _upsert(self, table: Dict, kind: str, key: str, obj) -> None:
        with self._lock:
            old = table.get(key)
            if old is None and not obj.metadata.creation_timestamp:
                obj.metadata.creation_timestamp = time.time()
            obj.metadata.resource_version = self._next_rv()
            table[key] = obj
            self._dispatch(Event(MODIFIED if old is not None else ADDED, kind, obj, old))

    def _deletion_copy(self, obj):
        """Deletion stamps a new revision (etcd semantics) — on a COPY.
        The stored instance is still referenced by every earlier
        ADDED/MODIFIED event sitting in watch caches and subscription
        replay windows; stamping it in place rewrites that committed
        history the moment a resumed stream lazily re-encodes it (a
        replayed create would claim the delete's revision, and the
        delete that follows gets collapsed as a duplicate by any
        rv-monotonic consumer — a lost deletion). Callers hold the
        store lock (``_next_rv``)."""
        final = shallow_copy(obj)
        final.metadata = shallow_copy(obj.metadata)
        final.metadata.resource_version = self._next_rv()
        return final

    def _delete(self, table: Dict, kind: str, key: str) -> None:
        """Finalizer-aware (apimachinery deletion semantics — shared by
        EVERY delete path, typed or generic): objects carrying
        finalizers are only marked; see ``delete_object``."""
        with self._lock:
            old = table.get(key)
            if old is None:
                return
            if old.metadata.finalizers:
                if old.metadata.deletion_timestamp is None:
                    marked = shallow_copy(old)
                    marked.metadata = shallow_copy(old.metadata)
                    marked.metadata.deletion_timestamp = time.time()
                    marked.metadata.resource_version = self._next_rv()
                    table[key] = marked
                    self._dispatch(Event(MODIFIED, kind, marked, old))
                return
            table.pop(key)
            final = self._deletion_copy(old)
            self._dispatch(Event(DELETED, kind, final))
            if kind == "CustomResourceDefinition":
                # definition gone -> kind unregistered, instances
                # cascade-deleted (apiextensions finalizer semantics)
                self._unregister_crd_locked(final)

    def add_node(self, node: Node) -> None:
        self._upsert(self._nodes, "Node", node.name, node)

    def update_node(self, node: Node) -> None:
        self._upsert(self._nodes, "Node", node.name, node)

    def delete_node(self, name: str) -> None:
        self._delete(self._nodes, "Node", name)

    def get_node(self, name: str) -> Optional[Node]:
        with self._lock:
            return self._nodes.get(name)

    def list_nodes(self) -> List[Node]:
        with self._lock:
            return list(self._nodes.values())

    def add_service(self, svc: Service) -> None:
        self._upsert(self._services, "Service", f"{svc.metadata.namespace}/{svc.name}", svc)

    def list_services(self, namespace: str) -> List[Service]:
        with self._lock:
            return [
                s for s in self._services.values()
                if s.metadata.namespace == namespace
            ]

    def add_replication_controller(self, rc: ReplicationController) -> None:
        self._upsert(self._rcs, "ReplicationController",
                     f"{rc.metadata.namespace}/{rc.metadata.name}", rc)

    def list_replication_controllers(self, namespace: str) -> List[ReplicationController]:
        with self._lock:
            return [
                r for r in self._rcs.values() if r.metadata.namespace == namespace
            ]

    def add_replica_set(self, rs: ReplicaSet) -> None:
        self._upsert(self._rss, "ReplicaSet",
                     f"{rs.metadata.namespace}/{rs.metadata.name}", rs)

    def list_replica_sets(self, namespace: str) -> List[ReplicaSet]:
        with self._lock:
            return [
                r for r in self._rss.values() if r.metadata.namespace == namespace
            ]

    def add_stateful_set(self, ss: StatefulSet) -> None:
        self._upsert(self._sss, "StatefulSet",
                     f"{ss.metadata.namespace}/{ss.metadata.name}", ss)

    def list_stateful_sets(self, namespace: str) -> List[StatefulSet]:
        with self._lock:
            return [
                s for s in self._sss.values() if s.metadata.namespace == namespace
            ]

    def add_pvc(self, pvc: PersistentVolumeClaim) -> None:
        self._upsert(self._pvcs, "PersistentVolumeClaim",
                     f"{pvc.namespace}/{pvc.name}", pvc)

    def get_pvc(self, namespace: str, name: str) -> Optional[PersistentVolumeClaim]:
        with self._lock:
            return self._pvcs.get(f"{namespace}/{name}")

    def add_pv(self, pv: PersistentVolume) -> None:
        self._upsert(self._pvs, "PersistentVolume", pv.name, pv)

    def get_pv(self, name: str) -> Optional[PersistentVolume]:
        with self._lock:
            return self._pvs.get(name)

    def list_pvs(self) -> List[PersistentVolume]:
        with self._lock:
            return list(self._pvs.values())

    def add_storage_class(self, sc: StorageClass) -> None:
        self._upsert(self._storage_classes, "StorageClass", sc.name, sc)

    def get_storage_class(self, name: str) -> Optional[StorageClass]:
        with self._lock:
            return self._storage_classes.get(name)

    def add_csi_node(self, cn: CSINode) -> None:
        self._upsert(self._csi_nodes, "CSINode", cn.metadata.name, cn)

    def get_csi_node(self, name: str) -> Optional[CSINode]:
        with self._lock:
            return self._csi_nodes.get(name)

    def delete_service(self, namespace: str, name: str) -> None:
        self._delete(self._services, "Service", f"{namespace}/{name}")

    def list_all_services(self) -> List[Service]:
        with self._lock:
            return list(self._services.values())

    def delete_replica_set(self, namespace: str, name: str) -> None:
        self._delete(self._rss, "ReplicaSet", f"{namespace}/{name}")

    def list_all_replica_sets(self) -> List[ReplicaSet]:
        with self._lock:
            return list(self._rss.values())

    def get_replica_set(self, namespace: str, name: str) -> Optional[ReplicaSet]:
        with self._lock:
            return self._rss.get(f"{namespace}/{name}")

    def list_all_replication_controllers(self) -> List[ReplicationController]:
        with self._lock:
            return list(self._rcs.values())

    def list_all_stateful_sets(self) -> List[StatefulSet]:
        with self._lock:
            return list(self._sss.values())

    def list_all_pvcs(self) -> List[PersistentVolumeClaim]:
        with self._lock:
            return list(self._pvcs.values())

    def list_storage_classes(self) -> List[StorageClass]:
        with self._lock:
            return list(self._storage_classes.values())

    def list_csi_nodes(self) -> List[CSINode]:
        with self._lock:
            return list(self._csi_nodes.values())

    def upsert_endpoints(self, ep: Endpoints) -> None:
        self._upsert(self._endpoints, "Endpoints",
                     f"{ep.namespace}/{ep.name}", ep)

    def delete_endpoints(self, namespace: str, name: str) -> None:
        self._delete(self._endpoints, "Endpoints", f"{namespace}/{name}")

    def get_endpoints(self, namespace: str, name: str) -> Optional[Endpoints]:
        with self._lock:
            return self._endpoints.get(f"{namespace}/{name}")

    def list_endpoints(self) -> List[Endpoints]:
        with self._lock:
            return list(self._endpoints.values())

    def add_deployment(self, d: Deployment) -> None:
        self._upsert(self._deployments, "Deployment", f"{d.namespace}/{d.name}", d)

    def update_deployment(self, d: Deployment) -> None:
        self._upsert(self._deployments, "Deployment", f"{d.namespace}/{d.name}", d)

    def delete_deployment(self, namespace: str, name: str) -> None:
        self._delete(self._deployments, "Deployment", f"{namespace}/{name}")

    def get_deployment(self, namespace: str, name: str) -> Optional[Deployment]:
        with self._lock:
            return self._deployments.get(f"{namespace}/{name}")

    def list_deployments(self) -> List[Deployment]:
        with self._lock:
            return list(self._deployments.values())

    def add_daemon_set(self, ds: DaemonSet) -> None:
        self._upsert(self._daemon_sets, "DaemonSet", f"{ds.namespace}/{ds.name}", ds)

    def delete_daemon_set(self, namespace: str, name: str) -> None:
        self._delete(self._daemon_sets, "DaemonSet", f"{namespace}/{name}")

    def list_daemon_sets(self) -> List[DaemonSet]:
        with self._lock:
            return list(self._daemon_sets.values())

    def add_job(self, job: Job) -> None:
        self._upsert(self._jobs, "Job", f"{job.namespace}/{job.name}", job)

    def delete_job(self, namespace: str, name: str) -> None:
        self._delete(self._jobs, "Job", f"{namespace}/{name}")

    def get_job(self, namespace: str, name: str) -> Optional[Job]:
        with self._lock:
            return self._jobs.get(f"{namespace}/{name}")

    def list_jobs(self) -> List[Job]:
        with self._lock:
            return list(self._jobs.values())

    # -- namespaces / quotas / service accounts / cron jobs -------------
    def add_namespace(self, ns: Namespace) -> None:
        self._upsert(self._namespaces, "Namespace", ns.name, ns)

    def get_namespace(self, name: str) -> Optional[Namespace]:
        with self._lock:
            return self._namespaces.get(name)

    def list_namespaces(self) -> List[Namespace]:
        with self._lock:
            return list(self._namespaces.values())

    def delete_namespace(self, name: str) -> None:
        self._delete(self._namespaces, "Namespace", name)

    def add_resource_quota(self, q: ResourceQuota) -> None:
        self._upsert(self._quotas, "ResourceQuota",
                     f"{q.namespace}/{q.name}", q)

    def get_resource_quota(self, namespace: str,
                           name: str) -> Optional[ResourceQuota]:
        with self._lock:
            return self._quotas.get(f"{namespace}/{name}")

    def list_resource_quotas(self) -> List[ResourceQuota]:
        with self._lock:
            return list(self._quotas.values())

    def add_service_account(self, sa: ServiceAccount) -> None:
        self._upsert(self._service_accounts, "ServiceAccount",
                     f"{sa.namespace}/{sa.name}", sa)

    def get_service_account(self, namespace: str,
                            name: str) -> Optional[ServiceAccount]:
        with self._lock:
            return self._service_accounts.get(f"{namespace}/{name}")

    def list_service_accounts(self) -> List[ServiceAccount]:
        with self._lock:
            return list(self._service_accounts.values())

    def add_cron_job(self, cj: CronJob) -> None:
        self._upsert(self._cron_jobs, "CronJob",
                     f"{cj.namespace}/{cj.name}", cj)

    def get_cron_job(self, namespace: str, name: str) -> Optional[CronJob]:
        with self._lock:
            return self._cron_jobs.get(f"{namespace}/{name}")

    def list_cron_jobs(self) -> List[CronJob]:
        with self._lock:
            return list(self._cron_jobs.values())

    def add_hpa(self, hpa: HorizontalPodAutoscaler) -> None:
        self._upsert(self._hpas, "HorizontalPodAutoscaler",
                     f"{hpa.namespace}/{hpa.name}", hpa)

    def get_hpa(self, namespace: str,
                name: str) -> Optional[HorizontalPodAutoscaler]:
        with self._lock:
            return self._hpas.get(f"{namespace}/{name}")

    def list_hpas(self) -> List[HorizontalPodAutoscaler]:
        with self._lock:
            return list(self._hpas.values())

    def add_endpoint_slice(self, es: EndpointSlice) -> None:
        self._upsert(self._endpoint_slices, "EndpointSlice",
                     f"{es.namespace}/{es.name}", es)

    def list_endpoint_slices(self) -> List[EndpointSlice]:
        with self._lock:
            return list(self._endpoint_slices.values())

    def update_replica_set(self, rs: ReplicaSet) -> None:
        self._upsert(self._rss, "ReplicaSet", f"{rs.namespace}/{rs.name}", rs)

    def set_pod_phase(self, namespace: str, name: str, phase: str,
                      pod_ip: str = "", host_ip: str = "") -> bool:
        """Pod status subresource update (the kubelet's status manager
        path): phase + network identity, dispatched as MODIFIED. Returns
        False if the pod no longer exists (REST layer's 404)."""
        with self._lock:
            key = f"{namespace}/{name}"
            pod = self._pods.get(key)
            if pod is None:
                return False
            new_pod = shallow_copy(pod)
            new_pod.status = shallow_copy(pod.status)
            if phase:
                new_pod.status.phase = phase
            if pod_ip:
                new_pod.status.pod_ip = pod_ip
            if host_ip:
                new_pod.status.host_ip = host_ip
            new_pod.metadata = shallow_copy(pod.metadata)
            new_pod.metadata.resource_version = self._next_rv()
            self._pods[key] = new_pod
            self._dispatch(Event(MODIFIED, "Pod", new_pod, pod))
            return True

    # RBAC objects (reference pkg/registry/rbac/)
    def add_role(self, r: Role) -> None:
        self._upsert(self._roles, "Role", f"{r.namespace}/{r.name}", r)

    def get_role(self, namespace: str, name: str) -> Optional[Role]:
        with self._lock:
            return self._roles.get(f"{namespace}/{name}")

    def list_roles(self, namespace: Optional[str] = None) -> List[Role]:
        with self._lock:
            return [
                r for r in self._roles.values()
                if namespace is None or r.namespace == namespace
            ]

    def add_cluster_role(self, r: ClusterRole) -> None:
        self._upsert(self._cluster_roles, "ClusterRole", r.name, r)

    def get_cluster_role(self, name: str) -> Optional[ClusterRole]:
        with self._lock:
            return self._cluster_roles.get(name)

    def list_cluster_roles(self) -> List[ClusterRole]:
        with self._lock:
            return list(self._cluster_roles.values())

    def add_role_binding(self, rb: RoleBinding) -> None:
        self._upsert(
            self._role_bindings, "RoleBinding",
            f"{rb.namespace}/{rb.name}", rb,
        )

    def list_role_bindings(
        self, namespace: Optional[str] = None
    ) -> List[RoleBinding]:
        with self._lock:
            return [
                rb for rb in self._role_bindings.values()
                if namespace is None or rb.namespace == namespace
            ]

    def add_cluster_role_binding(self, crb: ClusterRoleBinding) -> None:
        self._upsert(
            self._cluster_role_bindings, "ClusterRoleBinding", crb.name, crb
        )

    def list_cluster_role_bindings(self) -> List[ClusterRoleBinding]:
        with self._lock:
            return list(self._cluster_role_bindings.values())

    def add_pdb(self, pdb: PodDisruptionBudget) -> None:
        self._upsert(self._pdbs, "PodDisruptionBudget",
                     f"{pdb.namespace}/{pdb.name}", pdb)

    def list_pdbs(self) -> List[PodDisruptionBudget]:
        with self._lock:
            return list(self._pdbs.values())

    # ------------------------------------------------------------------
    # generic typed-object surface (the REST registry's view;
    # reference generic/registry/store.go serves every resource through
    # one generic Store parameterized by strategy)
    _KIND_TABLES = {
        "Pod": ("_pods", True),
        "Node": ("_nodes", False),
        "Service": ("_services", True),
        "Endpoints": ("_endpoints", True),
        "ReplicaSet": ("_rss", True),
        "ReplicationController": ("_rcs", True),
        "StatefulSet": ("_sss", True),
        "Deployment": ("_deployments", True),
        "DaemonSet": ("_daemon_sets", True),
        "Job": ("_jobs", True),
        "PersistentVolumeClaim": ("_pvcs", True),
        "PersistentVolume": ("_pvs", False),
        "StorageClass": ("_storage_classes", False),
        "CSINode": ("_csi_nodes", False),
        "PodDisruptionBudget": ("_pdbs", True),
        "Event": ("_api_events", True),
        "Namespace": ("_namespaces", False),
        "ResourceQuota": ("_quotas", True),
        "ServiceAccount": ("_service_accounts", True),
        "CronJob": ("_cron_jobs", True),
        "HorizontalPodAutoscaler": ("_hpas", True),
        "EndpointSlice": ("_endpoint_slices", True),
        "Role": ("_roles", True),
        "ClusterRole": ("_cluster_roles", False),
        "RoleBinding": ("_role_bindings", True),
        "ClusterRoleBinding": ("_cluster_role_bindings", False),
        "CustomResourceDefinition": ("_crds", False),
        "MutatingWebhookConfiguration": ("_mutating_webhooks", False),
        "ValidatingWebhookConfiguration": ("_validating_webhooks", False),
        "Secret": ("_secrets", True),
        "ConfigMap": ("_config_maps", True),
        "CertificateSigningRequest": ("_csrs", False),
        "PriorityClass": ("_priority_classes", False),
    }

    # ------------------------------------------------------------------
    # Event objects (the operator's debugging surface)
    def list_events(self, namespace: Optional[str] = None,
                    involved_name: Optional[str] = None):
        with self._lock:
            out = []
            for ev in self._api_events.values():
                if namespace is not None and ev.metadata.namespace != namespace:
                    continue
                if involved_name is not None and \
                        ev.involved_object.name != involved_name:
                    continue
                out.append(ev)
            return out

    def prune_expired_events(self, now: Optional[float] = None) -> int:
        """Drop Event objects past their TTL (reference --event-ttl).
        Called periodically by the EventRecorder's flush loop."""
        now = now if now is not None else time.time()
        removed = 0
        with self._lock:
            stale = [
                key for key, ev in self._api_events.items()
                if now - (ev.last_timestamp or ev.metadata.creation_timestamp)
                > self.event_ttl
            ]
            for key in stale:
                old = self._api_events.pop(key)
                self._dispatch(Event(DELETED, "Event",
                                     self._deletion_copy(old)))
                removed += 1
        return removed

    def _kind_entry(self, kind: str) -> Tuple[Dict[str, Any], bool]:
        """(table, namespaced) for typed OR runtime-registered kinds."""
        entry = self._KIND_TABLES.get(kind)
        if entry is not None:
            return getattr(self, entry[0]), entry[1]
        got = self._custom_kinds.get(kind)
        if got is None:
            raise KeyError(f"unknown kind {kind!r}")
        return got

    def _table_key(self, kind: str, namespace: str, name: str):
        table, namespaced = self._kind_entry(kind)
        key = f"{namespace}/{name}" if namespaced else name
        return table, key

    def kind_is_namespaced(self, kind: str) -> bool:
        return self._kind_entry(kind)[1]

    def known_kinds(self) -> List[str]:
        return list(self._KIND_TABLES) + list(self._custom_kinds)

    # -- CRD analog (runtime kind registration) ------------------------
    def custom_kind_names(self) -> List[str]:
        with self._lock:
            return list(self._custom_kinds)

    def custom_plural_to_kind(self, plural: str) -> Optional[str]:
        with self._lock:
            return self._custom_plurals.get(plural)

    def custom_route(self, group: str, version: str,
                     plural: str) -> Optional[str]:
        """Resolve /apis/<group>/<version>/<plural> to a custom kind —
        only when the CRD declares that group AND serves that version
        (an unserved version is a 404, apiextensions serving rules)."""
        with self._lock:
            kind = self._custom_plurals.get(plural)
            if kind is None:
                return None
            crd_group, served = self._custom_served.get(kind, ("", ()))
            if crd_group != group or version not in served:
                return None
            return kind

    def custom_served_versions(self, kind: str) -> Tuple[str, tuple]:
        with self._lock:
            return self._custom_served.get(kind, ("", ()))

    def custom_kind_to_plural(self, kind: str) -> Optional[str]:
        """Reverse plural lookup for a runtime-registered kind — the
        authoritative vocabulary for authz rules and webhook rule
        matching (naive ``lower()+"s"`` mis-pluralizes -y/-s/-x kinds,
        which for authz is a policy-bypass-shaped bug)."""
        with self._lock:
            for plural, k in self._custom_plurals.items():
                if k == kind:
                    return plural
        return None

    def _register_crd_locked(self, crd) -> None:
        kind = crd.names.kind
        plural = crd.names.plural
        if not kind:
            raise ValidationError("CRD names.kind is required")
        if not plural:
            # the reference makes spec.names.plural mandatory
            # (apiextensions validation); guessing it here would put a
            # wrong word in the authz/webhook rule vocabulary
            raise ValidationError("CRD names.plural is required")
        if kind in self._KIND_TABLES:
            raise ValidationError(f"kind {kind!r} shadows a built-in kind")
        versions = list(getattr(crd, "versions", ()) or ())
        if versions:
            # apiextensions validation: exactly one storage version,
            # at least one served
            if sum(1 for v in versions if v.storage) != 1:
                raise ValidationError(
                    "CRD must have exactly one storage version")
            if not any(v.served for v in versions):
                raise ValidationError(
                    "CRD must serve at least one version")
        namespaced = crd.scope != "Cluster"
        existing = self._custom_kinds.get(kind)
        table = existing[0] if existing is not None else {}
        self._custom_kinds[kind] = (table, namespaced)
        # group-route serving metadata: (group, served version names)
        served = tuple(v.name for v in versions if v.served) \
            if versions else (("v1",) if crd.group else ())
        self._custom_served[kind] = (crd.group, served)
        # a re-registration (CRD update) may have renamed the plural
        self._custom_plurals = {
            p: k for p, k in self._custom_plurals.items() if k != kind
        }
        self._custom_plurals[plural] = kind

    def _unregister_crd_locked(self, crd) -> None:
        kind = crd.names.kind
        got = self._custom_kinds.pop(kind, None)
        self._custom_served.pop(kind, None)
        self._custom_plurals = {
            p: k for p, k in self._custom_plurals.items() if k != kind
        }
        if got is None:
            return
        # cascade: instances die with their definition (the reference
        # apiextensions finalizer deletes all CRs before the CRD goes)
        table, _ = got
        doomed = [self._deletion_copy(obj) for obj in table.values()]
        table.clear()
        for obj in doomed:
            self._dispatch(Event(DELETED, kind, obj))

    def current_rv(self) -> int:
        with self._lock:
            return self._rv

    def create_object(self, kind: str, obj) -> Any:
        with self._lock:
            table, key = self._table_key(
                kind, obj.metadata.namespace, obj.metadata.name
            )
            if key in table:
                raise ValueError(f"{kind} {key!r} already exists")
            if kind == "CustomResourceDefinition":
                # validates AND registers the new kind's table + plural
                # route (apiextensions: creating the CRD IS the
                # registration; rejected CRDs never get stored)
                self._register_crd_locked(obj)
            if not obj.metadata.creation_timestamp:
                obj.metadata.creation_timestamp = time.time()
            obj.metadata.resource_version = self._next_rv()
            table[key] = obj
            self._dispatch(Event(ADDED, kind, obj))
            return obj

    def create_objects_bulk(self, kind: str, objs: List[Any]) -> int:
        """Bulk create for high-volume kinds (the event recorder's
        flush): ONE lock acquisition and ONE batched watch delivery for
        N objects, like ``create_pods``. Name collisions are skipped
        (the single-object path's drop-on-ValueError semantics), other
        objects still land. Returns the number created."""
        events: List[Event] = []
        with self._lock:
            for obj in objs:
                table, key = self._table_key(
                    kind, obj.metadata.namespace, obj.metadata.name
                )
                if key in table:
                    continue
                if not obj.metadata.creation_timestamp:
                    obj.metadata.creation_timestamp = time.time()
                obj.metadata.resource_version = self._next_rv()
                table[key] = obj
                events.append(Event(ADDED, kind, obj))
            self._dispatch_many(events)
        return len(events)

    def update_object(self, kind: str, obj, expect_rv: Optional[str] = None) -> Any:
        """Optimistic-concurrency update: fails on missing object or, when
        expect_rv is given, on a resourceVersion conflict (HTTP 409 path —
        reference GuaranteedUpdate's revision precondition)."""
        with self._lock:
            table, key = self._table_key(
                kind, obj.metadata.namespace, obj.metadata.name
            )
            old = table.get(key)
            if old is None:
                raise KeyError(f"{kind} {key!r} not found")
            if expect_rv and old.metadata.resource_version != expect_rv:
                raise ConflictError(
                    f"{kind} {key!r}: resourceVersion conflict "
                    f"(have {old.metadata.resource_version}, want {expect_rv})"
                )
            if kind == "CustomResourceDefinition":
                # re-register: scope/plural changes take effect (the
                # instance table is carried over)
                self._register_crd_locked(obj)
            obj.metadata.resource_version = self._next_rv()
            table[key] = obj
            self._dispatch(Event(MODIFIED, kind, obj, old))
            return obj

    def delete_object(self, kind: str, namespace: str, name: str) -> bool:
        """Finalizer-aware delete (apimachinery deletion semantics): an
        object carrying finalizers is only MARKED for deletion
        (``deletionTimestamp`` set, MODIFIED event) — the controllers
        owning the finalizers observe, do their cleanup, and call
        ``remove_finalizer``; the physical delete happens when the last
        finalizer clears. The typed helpers share these semantics via
        ``_delete``."""
        with self._lock:
            table, key = self._table_key(kind, namespace, name)
            if table.get(key) is None:
                return False
        self._delete(table, kind, key)
        return True

    def mutate_object(self, kind: str, namespace: str, name: str,
                     mutate, retries: int = 8):
        """Read-modify-write with optimistic concurrency (the reference's
        ``GuaranteedUpdate`` retry loop): ``mutate(fresh_copy)`` edits a
        shallow-copied object (metadata/status pre-copied) and the write
        CASes on the resourceVersion read. Concurrent writers — e.g. the
        attachdetach controller and a kubelet's image GC both updating
        one Node's status — retry instead of clobbering each other's
        fields. ``mutate`` may return False to abort (no write). Returns
        the stored object, or None when absent/aborted."""
        for _ in range(retries):
            current = self.get_object(kind, namespace, name)
            if current is None:
                return None
            updated = shallow_copy(current)
            updated.metadata = shallow_copy(current.metadata)
            if hasattr(current, "status"):
                updated.status = shallow_copy(current.status)
            if mutate(updated) is False:
                return None
            try:
                return self.update_object(
                    kind, updated,
                    expect_rv=current.metadata.resource_version,
                )
            except ConflictError:
                continue
        raise ConflictError(
            f"{kind} {namespace}/{name}: mutate_object retries exhausted"
        )

    def add_finalizer(self, kind: str, namespace: str, name: str,
                      finalizer: str) -> bool:
        """Attach a finalizer (protection controllers do this on ADD)."""
        with self._lock:
            table, key = self._table_key(kind, namespace, name)
            obj = table.get(key)
            if obj is None or finalizer in obj.metadata.finalizers:
                return False
            updated = shallow_copy(obj)
            updated.metadata = shallow_copy(obj.metadata)
            updated.metadata.finalizers = (
                list(obj.metadata.finalizers) + [finalizer]
            )
            updated.metadata.resource_version = self._next_rv()
            table[key] = updated
            self._dispatch(Event(MODIFIED, kind, updated, obj))
            return True

    def remove_finalizer(self, kind: str, namespace: str, name: str,
                         finalizer: str) -> bool:
        """Clear a finalizer; performs the pending physical delete when
        it was the last one on a deletion-marked object."""
        with self._lock:
            table, key = self._table_key(kind, namespace, name)
            obj = table.get(key)
            if obj is None or finalizer not in obj.metadata.finalizers:
                return False
            remaining = [f for f in obj.metadata.finalizers
                         if f != finalizer]
            if not remaining and obj.metadata.deletion_timestamp is not None:
                table.pop(key)
                final = self._deletion_copy(obj)
                final.metadata.finalizers = remaining
                self._dispatch(Event(DELETED, kind, final))
                return True
            updated = shallow_copy(obj)
            updated.metadata = shallow_copy(obj.metadata)
            updated.metadata.finalizers = remaining
            updated.metadata.resource_version = self._next_rv()
            table[key] = updated
            self._dispatch(Event(MODIFIED, kind, updated, obj))
            return True

    def _lease_object(self, name: str, lease: "_Lease"):
        """Synthesize the coordination.k8s.io/v1 view of an internal
        lease (leader election + node heartbeats) — `kubectl get
        leases` observability; writes still go through
        try_acquire_or_renew (the holders' fast path)."""
        from kubernetes_tpu.api.types import Lease, ObjectMeta

        return Lease(
            metadata=ObjectMeta(name=name, namespace="kube-system"),
            holder_identity=lease.holder,
            lease_duration_seconds=lease.duration,
            renew_time=lease.renew_time,
        )

    def get_object(self, kind: str, namespace: str, name: str):
        if kind == "Lease":
            # synthesized leases all live in kube-system; a lookup
            # scoped elsewhere must miss like any namespaced kind
            if namespace not in ("", "kube-system"):
                return None
            with self._lock:
                lease = self._leases.get(name)
                return self._lease_object(name, lease) \
                    if lease is not None else None
        with self._lock:
            table, key = self._table_key(kind, namespace, name)
            return table.get(key)

    def list_objects(self, kind: str, namespace: Optional[str] = None) -> List[Any]:
        return self.list_objects_with_rv(kind, namespace)[0]

    def list_objects_with_rv(
        self, kind: str, namespace: Optional[str] = None
    ) -> Tuple[List[Any], int]:
        """List + the RV the list is consistent at, atomically — the
        List+Watch bootstrap contract (a watch from this RV misses
        nothing that isn't already in the list)."""
        if kind == "Lease":
            if namespace is not None and namespace != "kube-system":
                with self._lock:
                    return [], self._rv
            with self._lock:
                return [
                    self._lease_object(name, lease)
                    for name, lease in sorted(self._leases.items())
                ], self._rv
        with self._lock:
            table, namespaced = self._kind_entry(kind)
            objs = list(table.values())
            if namespace is not None and namespaced:
                objs = [o for o in objs if o.metadata.namespace == namespace]
            return objs, self._rv

    # ------------------------------------------------------------------
    # volume binding support (SchedulerVolumeBinder assume/commit)
    def assume_pv_bound(self, pv_name: str, pvc_key: str) -> None:
        with self._lock:
            self._assumed_pvs[pv_name] = pvc_key

    def revert_assumed_pv(self, pv_name: str) -> None:
        with self._lock:
            self._assumed_pvs.pop(pv_name, None)

    def bind_pv(self, pv_name: str, pvc_namespace: str, pvc_name: str) -> bool:
        with self._lock:
            pv = self._pvs.get(pv_name)
            pvc = self._pvcs.get(f"{pvc_namespace}/{pvc_name}")
            if pv is None or pvc is None:
                return False
            pv.claim_ref = f"{pvc_namespace}/{pvc_name}"
            pv.phase = "Bound"
            pvc.volume_name = pv_name
            pvc.phase = "Bound"
            self._assumed_pvs.pop(pv_name, None)
            self._dispatch(Event(MODIFIED, "PersistentVolume", pv))
            self._dispatch(Event(MODIFIED, "PersistentVolumeClaim", pvc))
            return True

    def register_log_source(self, node_name: str, fn: Callable) -> None:
        with self._lock:
            self._log_sources[node_name] = fn

    def unregister_log_source(self, node_name: str) -> None:
        with self._lock:
            self._log_sources.pop(node_name, None)

    def log_source(self, node_name: str) -> Optional[Callable]:
        with self._lock:
            return self._log_sources.get(node_name)

    # pods/exec providers (the apiserver proxies exec requests to the
    # owning kubelet, like the reference's /exec SPDY dial to the node)
    def register_exec_source(self, node_name: str, fn: Callable) -> None:
        with self._lock:
            self._exec_sources[node_name] = fn

    def unregister_exec_source(self, node_name: str) -> None:
        with self._lock:
            self._exec_sources.pop(node_name, None)

    def exec_source(self, node_name: str) -> Optional[Callable]:
        with self._lock:
            return self._exec_sources.get(node_name)

    # pods/portforward providers (apiserver → owning kubelet → runtime
    # port, the SPDY stream dial collapsed to request/response)
    def register_portforward_source(self, node_name: str,
                                    fn: Callable) -> None:
        with self._lock:
            self._portforward_sources[node_name] = fn

    def unregister_portforward_source(self, node_name: str) -> None:
        with self._lock:
            self._portforward_sources.pop(node_name, None)

    def portforward_source(self, node_name: str) -> Optional[Callable]:
        with self._lock:
            return self._portforward_sources.get(node_name)

    def unbind_pv(self, pv_name: str, pvc_namespace: str,
                  pvc_name: str) -> bool:
        """Exact inverse of ``bind_pv`` for a pair it just bound — the
        batch commit's partial-failure rollback (the serial path's
        Unreserve analog). Refuses to touch a pair that is not bound to
        each other."""
        with self._lock:
            pv = self._pvs.get(pv_name)
            pvc = self._pvcs.get(f"{pvc_namespace}/{pvc_name}")
            if pv is None or pvc is None:
                return False
            if pv.claim_ref != f"{pvc_namespace}/{pvc_name}" or \
                    pvc.volume_name != pv_name:
                return False
            pv.claim_ref = None
            pv.phase = "Available"
            pvc.volume_name = ""
            pvc.phase = "Pending"
            self._dispatch(Event(MODIFIED, "PersistentVolume", pv))
            self._dispatch(Event(MODIFIED, "PersistentVolumeClaim", pvc))
            return True

    # ------------------------------------------------------------------
    # Lease objects (leader election; reference client-go leaderelection)
    def try_acquire_or_renew(
        self, name: str, holder: str, now: float, duration: float
    ) -> bool:
        with self._lock:
            lease = self._leases.get(name)
            if (
                lease is None
                or lease.holder == holder
                or now - lease.renew_time > lease.duration
            ):
                self._leases[name] = _Lease(holder, now, duration)
                return True
            return False

    def lease_holder(self, name: str) -> Optional[str]:
        with self._lock:
            lease = self._leases.get(name)
            return lease.holder if lease else None

    def lease_info(self, name: str):
        """(holder, renew_time) without touching the lease, or None."""
        with self._lock:
            lease = self._leases.get(name)
            return (lease.holder, lease.renew_time) if lease else None
