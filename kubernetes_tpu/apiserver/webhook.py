"""Admission webhooks: out-of-process mutating/validating admission
(reference ``staging/src/k8s.io/apiserver/pkg/admission/plugin/webhook/
mutating/dispatcher.go:75`` + ``validating/dispatcher.go``): the API
server's primary extension mechanism alongside CRDs.

``WebhookAdmission`` sits in the admission chain; on every request it
consults the store's Mutating/ValidatingWebhookConfiguration objects,
POSTs an AdmissionReview to each matching hook, applies returned JSON
patches (mutating phase), and rejects on a disallowed review
(validating phase). Call failures honor the hook's failurePolicy:
``Fail`` rejects the request, ``Ignore`` skips the hook — the same
availability/safety trade the reference exposes.
"""

from __future__ import annotations

import base64
import json
import logging
import urllib.error
import urllib.request
from typing import Any, Dict, List

from kubernetes_tpu.api.serialization import from_wire, to_wire
from kubernetes_tpu.apiserver.admission import (
    AdmissionError,
    AdmissionPlugin,
    AdmissionRequest,
)

_logger = logging.getLogger(__name__)


def _rule_matches(rule, operation: str, resource: str) -> bool:
    ops = rule.operations or ["*"]
    res = rule.resources or ["*"]
    return ("*" in ops or operation in ops) and (
        "*" in res or resource in res
    )


def _hook_matches(hook, operation: str, resource: str) -> bool:
    return any(_rule_matches(r, operation, resource) for r in hook.rules) \
        if hook.rules else True


def apply_json_patch(doc: Any, patch: List[Dict[str, Any]]) -> Any:
    """RFC 6902 JSON Patch: add / replace / remove / test / move /
    copy, with the RFC's error semantics (replace and remove require
    the path to exist; a failed test aborts the whole patch). Paths are
    '/'-separated with ~0/~1 escapes; '-' appends. Shared by webhook
    mutation responses and the apiserver's PATCH verb."""
    def walk(path: str, create: bool = False):
        parts = [
            p.replace("~1", "/").replace("~0", "~")
            for p in path.split("/")[1:]
        ]
        if not parts:
            raise AdmissionError(f"json patch: empty path {path!r}")
        parent = doc
        for p in parts[:-1]:
            if isinstance(parent, list):
                parent = parent[int(p)]
            elif create:
                parent = parent.setdefault(p, {})
            else:
                if p not in parent:
                    raise AdmissionError(
                        f"json patch: path {path!r} does not exist")
                parent = parent[p]
        return parent, parts[-1]

    def get_at(parent, leaf):
        if isinstance(parent, list):
            i = int(leaf)
            if not 0 <= i < len(parent):
                raise AdmissionError(
                    f"json patch: index {leaf} out of range")
            return parent[i]
        if leaf not in parent:
            raise AdmissionError(
                f"json patch: member {leaf!r} does not exist")
        return parent[leaf]

    def remove_at(parent, leaf):
        value = get_at(parent, leaf)
        if isinstance(parent, list):
            parent.pop(int(leaf))
        else:
            del parent[leaf]
        return value

    def add_at(parent, leaf, value):
        if isinstance(parent, list):
            if leaf == "-":
                parent.append(value)
            else:
                parent.insert(int(leaf), value)
        else:
            parent[leaf] = value

    for op in patch:
        kind = op.get("op")
        path = op.get("path", "")
        if kind == "add":
            parent, leaf = walk(path, create=True)
            add_at(parent, leaf, op["value"])
        elif kind == "replace":
            parent, leaf = walk(path)
            get_at(parent, leaf)        # must exist (RFC 6902 §4.3)
            if isinstance(parent, list):
                parent[int(leaf)] = op["value"]
            else:
                parent[leaf] = op["value"]
        elif kind == "remove":
            parent, leaf = walk(path)
            remove_at(parent, leaf)
        elif kind == "test":
            parent, leaf = walk(path)
            if get_at(parent, leaf) != op.get("value"):
                raise AdmissionError(
                    f"json patch: test failed at {path!r}")
        elif kind in ("move", "copy"):
            from_path = op.get("from", "")
            fparent, fleaf = walk(from_path)
            value = get_at(fparent, fleaf)
            if kind == "move":
                remove_at(fparent, fleaf)
            else:
                import copy as _copy

                value = _copy.deepcopy(value)
            parent, leaf = walk(path, create=True)
            add_at(parent, leaf, value)
        else:
            raise AdmissionError(f"json patch: unsupported op {kind!r}")
    return doc


class WebhookAdmission(AdmissionPlugin):
    """Dispatches to registered webhook configurations. Mutating hooks
    run in the chain's mutating pass, validating hooks in the
    validating pass (reference mutating-before-validating ordering)."""

    name = "Webhook"

    def __init__(self, store):
        self.store = store

    # -- wire ----------------------------------------------------------
    def _call(self, hook, req: AdmissionRequest) -> Dict[str, Any]:
        review = {
            "kind": "AdmissionReview",
            "apiVersion": "admission.k8s.io/v1",
            "request": {
                "uid": req.obj.metadata.uid,
                "kind": {"kind": req.kind},
                "namespace": req.namespace,
                "operation": req.operation,
                "userInfo": {"username": req.user},
                "object": to_wire(req.obj),
            },
        }
        data = json.dumps(review).encode()
        http_req = urllib.request.Request(
            hook.url, data=data, method="POST",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(
            http_req, timeout=max(1, hook.timeout_seconds)
        ) as resp:
            return json.loads(resp.read() or b"{}")

    def _dispatch(self, req: AdmissionRequest, configs,
                  mutating: bool) -> None:
        from kubernetes_tpu.apiserver.rest import KIND_TO_PLURAL

        resource = KIND_TO_PLURAL.get(req.kind)
        if resource is None:
            # CRD kinds match webhook rules by their DECLARED plural
            # (mandatory on the CRD); naive pluralization would let a
            # "Policy" CRD slip past a "policies" rule
            resource = self.store.custom_kind_to_plural(req.kind) \
                or req.kind.lower() + "s"
        if req.subresource:
            # upstream rule matching: status writes match only rules
            # naming "pods/status", never bare "pods"
            resource = f"{resource}/{req.subresource}"
        for cfg in configs:
            for hook in cfg.webhooks:
                if not _hook_matches(hook, req.operation, resource):
                    continue
                try:
                    review = self._call(hook, req)
                except (urllib.error.URLError, OSError, TimeoutError,
                        json.JSONDecodeError) as e:
                    if hook.failure_policy == "Ignore":
                        _logger.warning(
                            "webhook %s unreachable (ignored): %s",
                            hook.name, e,
                        )
                        continue
                    raise AdmissionError(
                        f"calling webhook {hook.name!r} failed: {e}"
                    )
                response = review.get("response") or {}
                if not response.get("allowed", False):
                    status = response.get("status") or {}
                    raise AdmissionError(
                        f"admission webhook {hook.name!r} denied the "
                        f"request: {status.get('message', 'denied')}"
                    )
                patch_b64 = response.get("patch")
                if mutating and patch_b64:
                    try:
                        patch = json.loads(base64.b64decode(patch_b64))
                        wire = apply_json_patch(to_wire(req.obj), patch)
                        req.obj = from_wire(wire, req.kind)
                    except AdmissionError:
                        raise
                    except Exception as e:  # noqa: BLE001 — bad patch
                        raise AdmissionError(
                            f"webhook {hook.name!r} returned an "
                            f"unappliable patch: {e}"
                        )

    # -- chain hooks ---------------------------------------------------
    def admit(self, req: AdmissionRequest) -> None:
        configs = self.store.list_objects("MutatingWebhookConfiguration")
        if configs:
            self._dispatch(req, configs, mutating=True)

    def validate(self, req: AdmissionRequest) -> None:
        configs = self.store.list_objects("ValidatingWebhookConfiguration")
        if configs:
            self._dispatch(req, configs, mutating=False)
