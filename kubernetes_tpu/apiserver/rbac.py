"""RBAC authorization (reference ``plugin/pkg/auth/authorizer/rbac/
rbac.go:159 New`` + the bootstrap policy in ``plugin/pkg/auth/authorizer/
rbac/bootstrappolicy/policy.go``).

The authorizer is a plain callable matching the API server's
``Authorizer`` seam (``apiserver/rest.py``): ``(user, verb, kind,
namespace) -> bool``. Evaluation order mirrors the reference's
VisitRulesFor: cluster-role bindings grant cluster-wide; role bindings
grant within their namespace, resolving either a namespaced Role or a
referenced ClusterRole (scoped down to the binding's namespace).

Group model: the reference's authenticator attaches groups to every
request; this server's bearer-token authn yields a bare username, so the
authorizer derives groups — every non-anonymous user is
``system:authenticated``, plus any static groups registered via
``add_user_to_group`` (bootstrap puts ``admin`` in ``system:masters``,
which short-circuits to allow, mirroring the superuser escape hatch).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Set

from kubernetes_tpu.api.types import (
    ClusterRole,
    ClusterRoleBinding,
    ObjectMeta,
    PolicyRule,
    RBACSubject,
    Role,
    RoleBinding,
    RoleRef,
)

AUTHENTICATED = "system:authenticated"
MASTERS = "system:masters"
ANONYMOUS = "system:anonymous"


def _verb_matches(rule: PolicyRule, verb: str) -> bool:
    return "*" in rule.verbs or verb in rule.verbs


def _resource_matches(rule: PolicyRule, resource: str) -> bool:
    return "*" in rule.resources or resource in rule.resources


def rule_allows(rule: PolicyRule, verb: str, resource: str,
                name: str = "") -> bool:
    """reference rbac.RuleAllows: verb AND resource must match; a rule
    with resourceNames further restricts to those objects."""
    if not _verb_matches(rule, verb) or not _resource_matches(rule, resource):
        return False
    if rule.resource_names:
        # a names-scoped rule only matches requests naming one of them
        # (list/watch carry no name and are NOT granted by named rules)
        return bool(name) and name in rule.resource_names
    return True


class RBACAuthorizer:
    """Store-backed RBAC authorizer, usable directly as the APIServer's
    ``authorizer=`` callable and by ``kubectl auth can-i``."""

    def __init__(self, store):
        self.store = store
        self._groups: Dict[str, Set[str]] = {}
        # bumped on static-group edits so the REST layer's decision
        # cache (rest.py authorize_cached) can observe policy changes
        # that don't flow through store events
        self.policy_gen = 0

    # -- group registry ------------------------------------------------
    def add_user_to_group(self, user: str, group: str) -> None:
        self._groups.setdefault(user, set()).add(group)
        self.policy_gen += 1

    def groups_for(self, user: str) -> Set[str]:
        groups = set(self._groups.get(user, ()))
        if user and user != ANONYMOUS:
            groups.add(AUTHENTICATED)
        # identity-derived groups, as the reference authenticators
        # attach them: node users join system:nodes (pkg/auth x509/
        # bootstrap authenticators), service accounts join
        # system:serviceaccounts and their namespace group
        # (pkg/serviceaccount/util.go MakeGroupNames)
        if user.startswith("system:node:"):
            groups.add("system:nodes")
        elif user.startswith("system:bootstrap:"):
            # bootstrap-token identities (kubeadm TLS bootstrap;
            # reference bootstrap token authenticator attaches
            # system:bootstrappers)
            groups.add("system:bootstrappers")
        elif user.startswith("system:serviceaccount:"):
            parts = user.split(":")
            if len(parts) == 4:
                groups.add("system:serviceaccounts")
                groups.add(f"system:serviceaccounts:{parts[2]}")
        return groups

    # -- evaluation ----------------------------------------------------
    def _subject_matches(self, subj: RBACSubject, user: str,
                         groups: Set[str]) -> bool:
        if subj.kind == "User":
            return subj.name == user or subj.name == "*"
        if subj.kind == "Group":
            return subj.name in groups
        if subj.kind == "ServiceAccount":
            # the token authn maps SA tokens to
            # system:serviceaccount:<ns>:<name> (reference style)
            return user == f"system:serviceaccount:{subj.namespace}:{subj.name}"
        return False

    def _binding_rules(self, ref: RoleRef,
                       namespace: str) -> List[PolicyRule]:
        if ref.kind == "ClusterRole":
            role = self.store.get_cluster_role(ref.name)
        else:
            role = self.store.get_role(namespace, ref.name)
        return role.rules if role is not None else []

    def authorize(self, user: str, verb: str, resource: str,
                  namespace: str = "", name: str = "") -> bool:
        """``resource`` accepts either the lowercase plural ("pods") or
        a kind name ("Pod" — the REST handler passes kinds); both are
        normalized to the plural the rules use."""
        resource = _normalize_resource(resource, self.store)
        groups = self.groups_for(user)
        if MASTERS in groups:
            return True
        for crb in self.store.list_cluster_role_bindings():
            if any(self._subject_matches(s, user, groups)
                   for s in crb.subjects):
                for rule in self._binding_rules(crb.role_ref, ""):
                    if rule_allows(rule, verb, resource, name):
                        return True
        if namespace:
            for rb in self.store.list_role_bindings(namespace):
                if any(self._subject_matches(s, user, groups)
                       for s in rb.subjects):
                    for rule in self._binding_rules(rb.role_ref, namespace):
                        if rule_allows(rule, verb, resource, name):
                            return True
        return False

    def __call__(self, user: str, verb: str, kind: str,
                 namespace: str) -> bool:
        return self.authorize(user, verb, kind, namespace)


def _normalize_resource(resource: str, store=None) -> str:
    from kubernetes_tpu.apiserver.rest import KIND_TO_PLURAL

    got = KIND_TO_PLURAL.get(resource)
    if got is not None:
        return got
    if resource[:1].isupper():
        # CRD-registered kinds use their DECLARED plural (mandatory on
        # the CRD names object) — a naive lower()+"s" would route a
        # kind like "Policy" to "policys", silently matching no rule
        # and turning a typo'd vocabulary into an authz bypass/lockout
        if store is not None:
            plural = store.custom_kind_to_plural(resource)
            if plural is not None:
                return plural
        # remaining uppercase names are the virtual built-ins with no
        # storage table (exactly "Binding" today), whose regular
        # pluralization is the rule vocabulary ("bindings")
        return resource.lower() + "s"
    return resource


# ---------------------------------------------------------------------------
# bootstrap policy (reference bootstrappolicy/policy.go ClusterRoles() +
# ClusterRoleBindings(): the control-plane components' standing grants)


def _rule(verbs: Iterable[str], resources: Iterable[str]) -> PolicyRule:
    return PolicyRule(verbs=list(verbs), resources=list(resources))


READ = ("get", "list", "watch")


def bootstrap_cluster_roles() -> List[ClusterRole]:
    return [
        ClusterRole(
            metadata=ObjectMeta(name="cluster-admin"),
            rules=[_rule(["*"], ["*"])],
        ),
        # reference policy.go "system:kube-scheduler"
        ClusterRole(
            metadata=ObjectMeta(name="system:kube-scheduler"),
            rules=[
                _rule(["create", "patch", "update"], ["events"]),
                _rule(READ + ("delete",), ["pods"]),
                _rule(["create"], ["bindings", "pods/binding"]),
                _rule(["patch", "update"], ["pods/status"]),
                _rule(READ, [
                    "nodes", "namespaces",
                    "persistentvolumes", "persistentvolumeclaims",
                    "services", "replicasets", "replicationcontrollers",
                    "statefulsets", "storageclasses", "csinodes",
                    "poddisruptionbudgets",
                ]),
                _rule(["update"], ["persistentvolumeclaims",
                                   "persistentvolumes"]),
                # leader-election lease (endpoints/lease model)
                _rule(["get", "create", "update"], ["leases", "endpoints"]),
            ],
        ),
        # reference policy.go "system:kube-controller-manager" (broad:
        # the controllers mutate most kinds; kept narrower than admin)
        ClusterRole(
            metadata=ObjectMeta(name="system:kube-controller-manager"),
            rules=[
                _rule(["*"], [
                    "pods", "nodes", "nodes/status", "services",
                    "endpoints", "endpointslices", "replicasets",
                    "replicationcontrollers", "statefulsets",
                    "deployments", "daemonsets", "jobs", "cronjobs",
                    "namespaces", "serviceaccounts", "resourcequotas",
                    "persistentvolumes", "persistentvolumeclaims",
                    "poddisruptionbudgets", "horizontalpodautoscalers",
                    "events", "leases",
                ]),
                _rule(READ, ["*"]),
            ],
        ),
        # reference policy.go "system:node-bootstrapper": a bootstrap
        # token may submit and watch its own CSR — nothing else
        ClusterRole(
            metadata=ObjectMeta(name="system:node-bootstrapper"),
            rules=[
                _rule(["create"] + list(READ),
                      ["certificatesigningrequests"]),
            ],
        ),
        # reference policy.go "system:node" (kubelet)
        ClusterRole(
            metadata=ObjectMeta(name="system:node"),
            rules=[
                _rule(READ, ["pods", "services", "endpoints",
                             "persistentvolumes",
                             "persistentvolumeclaims", "configmaps",
                             "secrets"]),
                _rule(["get", "patch", "update"],
                      ["nodes", "nodes/status"]),
                _rule(["create"], ["nodes"]),
                _rule(["patch", "update"], ["pods/status"]),
                _rule(["create", "patch", "update"], ["events"]),
                _rule(["delete"], ["pods"]),  # eviction
            ],
        ),
    ]


def bootstrap_cluster_role_bindings() -> List[ClusterRoleBinding]:
    def bind(name: str, role: str, subject: RBACSubject) -> ClusterRoleBinding:
        return ClusterRoleBinding(
            metadata=ObjectMeta(name=name),
            subjects=[subject],
            role_ref=RoleRef(kind="ClusterRole", name=role),
        )

    return [
        bind("system:kube-scheduler", "system:kube-scheduler",
             RBACSubject(kind="User", name="system:kube-scheduler")),
        bind("system:kube-controller-manager",
             "system:kube-controller-manager",
             RBACSubject(kind="User",
                         name="system:kube-controller-manager")),
        bind("system:nodes", "system:node",
             RBACSubject(kind="Group", name="system:nodes")),
        bind("kubeadm:node-bootstrappers", "system:node-bootstrapper",
             RBACSubject(kind="Group", name="system:bootstrappers")),
    ]


def provision_bootstrap_policy(store, authorizer: Optional[RBACAuthorizer]
                               = None) -> RBACAuthorizer:
    """Install the bootstrap roles/bindings and return a ready
    authorizer (admin lands in system:masters — the superuser group the
    reference's authorizer honors before RBAC evaluation)."""
    for role in bootstrap_cluster_roles():
        if store.get_cluster_role(role.name) is None:
            store.add_cluster_role(role)
    existing = {b.name for b in store.list_cluster_role_bindings()}
    for crb in bootstrap_cluster_role_bindings():
        if crb.name not in existing:
            store.add_cluster_role_binding(crb)
    authorizer = authorizer or RBACAuthorizer(store)
    authorizer.add_user_to_group("admin", MASTERS)
    return authorizer
