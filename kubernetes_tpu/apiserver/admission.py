"""Admission control chain for the REST path.

Behavioral equivalent of the reference's admission stage in the apiserver
handler chain (``staging/src/k8s.io/apiserver/pkg/admission``): after
authn/authz and before the registry write, every mutating request passes
through an ordered chain of admission plugins, each of which may mutate
the object (``MutationInterface``) and/or reject it
(``ValidationInterface``). Built-ins here mirror the upstream plugins the
scheduling path actually feels:

- ``NamespaceLifecycle`` — reject creates in terminating/absent namespaces
  (``plugin/pkg/admission/namespace/lifecycle``)
- ``DefaultTolerationSeconds`` — add default 300s tolerations for the
  not-ready/unreachable NoExecute taints to every pod
  (``plugin/pkg/admission/defaulttolerationseconds``)
- ``LimitRanger``-style request defaulting — containers with no cpu/mem
  request get namespace defaults so the scheduler's fit math sees nonzero
  vectors (``plugin/pkg/admission/limitranger``)
- ``TaintNodesByCondition``-adjacent ``PodPriority`` resolution — map
  priorityClassName → numeric priority (``plugin/pkg/admission/priority``)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from kubernetes_tpu.api.resource import parse_quantity
from kubernetes_tpu.api.types import Pod, Toleration

CREATE, UPDATE, DELETE = "CREATE", "UPDATE", "DELETE"


class AdmissionError(Exception):
    """Request rejected by an admission plugin (HTTP 403/422 at the REST
    layer)."""


@dataclass
class AdmissionRequest:
    operation: str
    kind: str
    namespace: str
    obj: Any
    old_obj: Any = None
    user: str = "system:anonymous"


class AdmissionPlugin:
    name = "plugin"

    def admit(self, req: AdmissionRequest) -> None:
        """Mutating pass — may modify req.obj in place."""

    def validate(self, req: AdmissionRequest) -> None:
        """Validating pass — raise AdmissionError to reject."""


class NamespaceLifecycle(AdmissionPlugin):
    name = "NamespaceLifecycle"

    def __init__(self, namespaces: Optional[Dict[str, str]] = None):
        # namespace -> phase ("Active"/"Terminating"); None = open world
        self.namespaces = namespaces

    def validate(self, req: AdmissionRequest) -> None:
        if self.namespaces is None or req.operation != CREATE:
            return
        phase = self.namespaces.get(req.namespace)
        if phase is None:
            raise AdmissionError(f"namespace {req.namespace!r} not found")
        if phase == "Terminating":
            raise AdmissionError(
                f"namespace {req.namespace!r} is terminating; "
                "no new objects may be created"
            )


class DefaultTolerationSeconds(AdmissionPlugin):
    name = "DefaultTolerationSeconds"

    NOT_READY = "node.kubernetes.io/not-ready"
    UNREACHABLE = "node.kubernetes.io/unreachable"

    def __init__(self, seconds: int = 300):
        self.seconds = seconds

    def admit(self, req: AdmissionRequest) -> None:
        if req.kind != "Pod" or req.operation != CREATE:
            return
        pod: Pod = req.obj
        tols = pod.spec.tolerations
        have = {
            t.key
            for t in tols
            if t.effect in ("NoExecute", "") and t.key in (self.NOT_READY, self.UNREACHABLE)
        }
        for key in (self.NOT_READY, self.UNREACHABLE):
            if key not in have:
                tols.append(
                    Toleration(
                        key=key,
                        operator="Exists",
                        effect="NoExecute",
                        toleration_seconds=self.seconds,
                    )
                )


class LimitRanger(AdmissionPlugin):
    name = "LimitRanger"

    def __init__(self, default_requests: Optional[Dict[str, str]] = None):
        self.defaults = {
            k: parse_quantity(v)
            for k, v in (default_requests or {}).items()
        }

    def admit(self, req: AdmissionRequest) -> None:
        if req.kind != "Pod" or req.operation != CREATE or not self.defaults:
            return
        pod: Pod = req.obj
        for c in pod.spec.containers:
            for res, qty in self.defaults.items():
                if res not in c.resources.requests:
                    c.resources.requests[res] = qty


class PodPriorityResolver(AdmissionPlugin):
    name = "Priority"

    def __init__(self, priority_classes: Optional[Dict[str, int]] = None):
        self.classes = dict(priority_classes or {})

    def admit(self, req: AdmissionRequest) -> None:
        if req.kind != "Pod" or req.operation != CREATE:
            return
        pod: Pod = req.obj
        cls = getattr(pod.spec, "priority_class_name", "")
        if cls:
            if cls not in self.classes:
                raise AdmissionError(f"no PriorityClass {cls!r}")
            pod.spec.priority = self.classes[cls]

    def validate(self, req: AdmissionRequest) -> None:
        pass


@dataclass
class AdmissionChain:
    """Ordered plugin chain: all mutating passes, then all validating
    passes (reference admission.NewChainHandler ordering)."""

    plugins: List[AdmissionPlugin] = field(default_factory=list)

    @classmethod
    def default(cls) -> "AdmissionChain":
        return cls(
            [
                NamespaceLifecycle(),
                DefaultTolerationSeconds(),
                LimitRanger(),
                PodPriorityResolver(),
            ]
        )

    def run(self, req: AdmissionRequest) -> Any:
        for p in self.plugins:
            p.admit(req)
        for p in self.plugins:
            p.validate(req)
        return req.obj
