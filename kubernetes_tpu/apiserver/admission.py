"""Admission control chain for the REST path.

Behavioral equivalent of the reference's admission stage in the apiserver
handler chain (``staging/src/k8s.io/apiserver/pkg/admission``): after
authn/authz and before the registry write, every mutating request passes
through an ordered chain of admission plugins, each of which may mutate
the object (``MutationInterface``) and/or reject it
(``ValidationInterface``). Built-ins here mirror the upstream plugins the
scheduling path actually feels:

- ``NamespaceLifecycle`` — reject creates in terminating/absent namespaces
  (``plugin/pkg/admission/namespace/lifecycle``)
- ``DefaultTolerationSeconds`` — add default 300s tolerations for the
  not-ready/unreachable NoExecute taints to every pod
  (``plugin/pkg/admission/defaulttolerationseconds``)
- ``LimitRanger``-style request defaulting — containers with no cpu/mem
  request get namespace defaults so the scheduler's fit math sees nonzero
  vectors (``plugin/pkg/admission/limitranger``)
- ``TaintNodesByCondition``-adjacent ``PodPriority`` resolution — map
  priorityClassName → numeric priority (``plugin/pkg/admission/priority``)
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from kubernetes_tpu.api.resource import parse_quantity
from kubernetes_tpu.api.types import Pod, Toleration

CREATE, UPDATE, DELETE = "CREATE", "UPDATE", "DELETE"


class AdmissionError(Exception):
    """Request rejected by an admission plugin (HTTP 403/422 at the REST
    layer)."""


@dataclass
class AdmissionRequest:
    operation: str
    kind: str
    namespace: str
    obj: Any
    old_obj: Any = None
    user: str = "system:anonymous"
    # "status" for pods/status writes etc. — webhook rule matching
    # treats "<plural>/<subresource>" as its own vocabulary entry (a
    # rule naming "pods" must NOT intercept kubelet status writes)
    subresource: str = ""


class AdmissionPlugin:
    name = "plugin"

    def admit(self, req: AdmissionRequest) -> None:
        """Mutating pass — may modify req.obj in place."""

    def validate(self, req: AdmissionRequest) -> None:
        """Validating pass — raise AdmissionError to reject."""


class NamespaceLifecycle(AdmissionPlugin):
    name = "NamespaceLifecycle"

    def __init__(self, namespaces: Optional[Dict[str, str]] = None,
                 store=None):
        # namespace -> phase ("Active"/"Terminating"); or a live store
        # (Namespace objects consulted per request). With neither, the
        # world is open. Deviation from upstream: a namespace with no
        # Namespace OBJECT stays open (the perf harness schedules into
        # "default" without creating namespace objects); only an
        # explicitly Terminating namespace rejects creates.
        self.namespaces = namespaces
        self.store = store

    def _phase(self, namespace: str) -> Optional[str]:
        if self.namespaces is not None:
            return self.namespaces.get(namespace)
        if self.store is not None:
            ns = self.store.get_namespace(namespace)
            return ns.phase if ns is not None else "__absent__"
        return None

    def validate(self, req: AdmissionRequest) -> None:
        if req.operation != CREATE or req.kind == "Namespace":
            return
        if self.namespaces is None and self.store is None:
            return
        phase = self._phase(req.namespace)
        if phase is None and self.namespaces is not None:
            raise AdmissionError(f"namespace {req.namespace!r} not found")
        if phase == "Terminating":
            raise AdmissionError(
                f"namespace {req.namespace!r} is terminating; "
                "no new objects may be created"
            )


class DefaultTolerationSeconds(AdmissionPlugin):
    name = "DefaultTolerationSeconds"

    NOT_READY = "node.kubernetes.io/not-ready"
    UNREACHABLE = "node.kubernetes.io/unreachable"

    def __init__(self, seconds: int = 300):
        self.seconds = seconds

    def admit(self, req: AdmissionRequest) -> None:
        if req.kind != "Pod" or req.operation != CREATE:
            return
        pod: Pod = req.obj
        tols = pod.spec.tolerations
        have = {
            t.key
            for t in tols
            if t.effect in ("NoExecute", "") and t.key in (self.NOT_READY, self.UNREACHABLE)
        }
        for key in (self.NOT_READY, self.UNREACHABLE):
            if key not in have:
                tols.append(
                    Toleration(
                        key=key,
                        operator="Exists",
                        effect="NoExecute",
                        toleration_seconds=self.seconds,
                    )
                )


class LimitRanger(AdmissionPlugin):
    name = "LimitRanger"

    def __init__(self, default_requests: Optional[Dict[str, str]] = None):
        self.defaults = {
            k: parse_quantity(v)
            for k, v in (default_requests or {}).items()
        }

    def admit(self, req: AdmissionRequest) -> None:
        if req.kind != "Pod" or req.operation != CREATE or not self.defaults:
            return
        pod: Pod = req.obj
        for c in pod.spec.containers:
            for res, qty in self.defaults.items():
                if res not in c.resources.requests:
                    c.resources.requests[res] = qty


# reference pkg/apis/scheduling/types.go built-ins
SYSTEM_PRIORITY_CLASSES = {
    "system-cluster-critical": 2000000000,
    "system-node-critical": 2000001000,
}


class PodPriorityResolver(AdmissionPlugin):
    """Priority admission (reference ``plugin/pkg/admission/priority/
    admission.go``): resolve ``priorityClassName`` → numeric priority
    from PriorityClass API objects (plus the two system built-ins); a
    pod naming no class gets the cluster's globalDefault class when one
    exists. A static dict may seed/override resolution (the harness's
    offline mode)."""

    name = "Priority"

    def __init__(self, priority_classes: Optional[Dict[str, int]] = None,
                 store=None):
        self.classes = dict(priority_classes or {})
        self.store = store

    def _resolve(self, name: str) -> Optional[int]:
        got = self.classes.get(name)
        if got is not None:
            return got
        got = SYSTEM_PRIORITY_CLASSES.get(name)
        if got is not None:
            return got
        if self.store is not None:
            pc = self.store.get_object("PriorityClass", "", name)
            if pc is not None:
                return pc.value
        return None

    def admit(self, req: AdmissionRequest) -> None:
        if req.kind != "Pod" or req.operation != CREATE:
            return
        pod: Pod = req.obj
        cls = getattr(pod.spec, "priority_class_name", "")
        if cls:
            value = self._resolve(cls)
            if value is None:
                raise AdmissionError(f"no PriorityClass {cls!r}")
            pod.spec.priority = value
        elif pod.spec.priority is None and self.store is not None:
            defaults = [
                pc for pc in self.store.list_objects("PriorityClass")
                if pc.global_default
            ]
            if defaults:
                # upstream picks the LOWEST value among multiple
                # globalDefault classes (admission.go: "we pick the one
                # with the lowest priority value") — not the newest
                chosen = min(defaults, key=lambda pc: pc.value)
                pod.spec.priority_class_name = chosen.name
                pod.spec.priority = chosen.value

    def validate(self, req: AdmissionRequest) -> None:
        pass


class ResourceQuotaAdmission(AdmissionPlugin):
    """Quota gatekeeping (``plugin/pkg/admission/resourcequota``): a pod
    CREATE that would push any quota dimension in its namespace past
    ``hard`` is rejected. Usage is charged SYNCHRONOUSLY here, like the
    upstream plugin's transactional quota evaluator: live usage is
    recomputed from the store's pods plus the in-flight charges this
    plugin has admitted but the registry hasn't persisted yet — the
    controller's async ``status.used`` is reporting, not enforcement
    (a burst of creates would race a status-based check)."""

    name = "ResourceQuota"

    PENDING_TTL = 30.0  # in-flight charge expiry (failed create path)

    def __init__(self, store=None):
        import threading

        self.store = store
        self._lock = threading.Lock()
        # (ns, name) -> (charge time, cpu_milli, mem) admitted but not
        # yet visible in the store
        self._pending: Dict[tuple, tuple] = {}

    def validate(self, req: AdmissionRequest) -> None:
        if self.store is None or req.kind != "Pod" or \
                req.operation != CREATE:
            return
        quotas = [
            q for q in self.store.list_resource_quotas()
            if q.namespace == req.namespace
        ]
        if not quotas:
            return
        import time as _time

        pod: Pod = req.obj
        cpu_milli = sum(
            int(c.resources.requests["cpu"].milli_value())
            for c in pod.spec.containers if "cpu" in c.resources.requests
        )
        mem = sum(
            int(c.resources.requests["memory"].value())
            for c in pod.spec.containers
            if "memory" in c.resources.requests
        )
        deltas = {
            "pods": 1,
            "requests.cpu": cpu_milli,
            "cpu": cpu_milli,
            "requests.memory": mem,
            "memory": mem,
        }
        with self._lock:
            now = _time.time()
            # namespace filter runs store-side (one pass under the store
            # lock) — a cluster-wide copy per quota'd CREATE would make
            # admission O(all pods) under this plugin-global lock
            live = [
                p for p in self.store.list_pods(namespace=req.namespace)
                if p.status.phase not in ("Succeeded", "Failed")
            ]
            # settle in-flight charges: visible in the store now
            # (checked against the entry's OWN namespace — an entry
            # from another namespace must not linger to TTL), or
            # expired (the create failed without a rollback call)
            self._pending = {
                k: v for k, v in self._pending.items()
                if now - v[0] < self.PENDING_TTL
                and self.store.get_pod(k[0], k[1]) is None
            }
            pend = [v for k, v in self._pending.items()
                    if k[0] == req.namespace]
            used_cpu = sum(
                int(c.resources.requests["cpu"].milli_value())
                for p in live for c in p.spec.containers
                if "cpu" in c.resources.requests
            ) + sum(v[1] for v in pend)
            used_mem = sum(
                int(c.resources.requests["memory"].value())
                for p in live for c in p.spec.containers
                if "memory" in c.resources.requests
            ) + sum(v[2] for v in pend)
            usage = {
                "pods": len(live) + len(pend),
                "requests.cpu": used_cpu, "cpu": used_cpu,
                "requests.memory": used_mem, "memory": used_mem,
            }
            for quota in quotas:
                for key, hard in quota.hard.items():
                    delta = deltas.get(key)
                    if delta is None:
                        continue
                    hard_v = (
                        int(hard.milli_value())
                        if key in ("requests.cpu", "cpu")
                        else int(hard.value())
                    )
                    if usage[key] + delta > hard_v:
                        raise AdmissionError(
                            f"exceeded quota {quota.name}: {key} "
                            f"(used {usage[key]} + requested {delta} > "
                            f"hard {hard_v})"
                        )
            # admitted: charge before releasing the lock
            self._pending[(req.namespace, pod.name)] = (
                now, cpu_milli, mem,
            )

    def rollback(self, req: AdmissionRequest) -> None:
        """Drop the in-flight charge immediately when the create fails
        downstream (later plugin rejection, store conflict) — without
        this the phantom charge blocks namespace headroom for up to
        PENDING_TTL seconds, spuriously rejecting creates that fit."""
        if req.kind != "Pod" or req.operation != CREATE:
            return
        with self._lock:
            self._pending.pop((req.namespace, req.obj.metadata.name), None)


class ServiceAccountAdmission(AdmissionPlugin):
    """ServiceAccount admission (reference ``plugin/pkg/admission/
    serviceaccount/admission.go:100 Admit``): pods that name no service
    account get the namespace's ``default`` account injected; a pod
    naming a NONEXISTENT account is rejected. Deviation from upstream
    (documented like NamespaceLifecycle's): the injected ``default`` is
    allowed to be absent — the serviceaccount controller provisions it
    asynchronously per namespace, and the perf harness schedules into
    namespaces that have no objects at all; only an EXPLICITLY named
    missing account rejects."""

    name = "ServiceAccount"

    DEFAULT = "default"

    def __init__(self, store=None):
        self.store = store

    def admit(self, req: AdmissionRequest) -> None:
        if req.kind != "Pod" or req.operation != CREATE:
            return
        pod: Pod = req.obj
        if not pod.spec.service_account_name:
            pod.spec.service_account_name = self.DEFAULT

    def validate(self, req: AdmissionRequest) -> None:
        if self.store is None or req.kind != "Pod" or \
                req.operation != CREATE:
            return
        pod: Pod = req.obj
        sa = pod.spec.service_account_name
        if sa and sa != self.DEFAULT and \
                self.store.get_service_account(req.namespace, sa) is None:
            raise AdmissionError(
                f"service account {req.namespace}/{sa} not found"
            )


class AlwaysPullImages(AdmissionPlugin):
    """Force imagePullPolicy=Always on every container (reference
    ``plugin/pkg/admission/alwayspullimages/admission.go``): in a
    multi-tenant cluster a pod must not reuse another tenant's
    node-cached private image just by naming it."""

    name = "AlwaysPullImages"

    def admit(self, req: AdmissionRequest) -> None:
        if req.kind != "Pod" or req.operation != CREATE:
            return
        pod: Pod = req.obj
        for c in list(pod.spec.containers) + list(pod.spec.init_containers):
            c.image_pull_policy = "Always"

    def validate(self, req: AdmissionRequest) -> None:
        if req.kind != "Pod" or req.operation != CREATE:
            return
        pod: Pod = req.obj
        for c in list(pod.spec.containers) + list(pod.spec.init_containers):
            if c.image_pull_policy != "Always":
                raise AdmissionError(
                    f"container {c.name!r}: imagePullPolicy must be "
                    f"Always"
                )


class EventRateLimit(AdmissionPlugin):
    """Server-side Event flood protection (reference
    ``plugin/pkg/admission/eventratelimit/admission.go``): a token
    bucket per source namespace; Events over the burst are rejected so
    a crash-looping component cannot swamp the store. Only the Server
    type limit is modeled (the reference's default config)."""

    name = "EventRateLimit"

    # bounded like the reference's LRU cache (eventratelimit defaults
    # to 4096 keys) — namespaces churn; their buckets must not leak
    MAX_BUCKETS = 4096

    def __init__(self, qps: float = 50.0, burst: int = 100):
        import threading
        import time as _time

        self.qps = qps
        self.burst = burst
        self._lock = threading.Lock()
        # ns -> (tokens, stamp), LRU-ordered
        self._buckets: "OrderedDict[str, tuple]" = OrderedDict()
        self._now = _time.monotonic

    def validate(self, req: AdmissionRequest) -> None:
        if req.kind != "Event" or req.operation != CREATE:
            return
        now = self._now()
        with self._lock:
            got = self._buckets.get(req.namespace)
            if got is not None:
                self._buckets.move_to_end(req.namespace)
            tokens, stamp = got if got is not None else \
                (float(self.burst), now)
            while len(self._buckets) >= self.MAX_BUCKETS:
                self._buckets.popitem(last=False)
            tokens = min(float(self.burst),
                         tokens + (now - stamp) * self.qps)
            if tokens < 1.0:
                self._buckets[req.namespace] = (tokens, now)
                raise AdmissionError(
                    f"event rate limit exceeded for namespace "
                    f"{req.namespace!r}"
                )
            self._buckets[req.namespace] = (tokens - 1.0, now)


IS_DEFAULT_CLASS_ANNOTATION = "storageclass.kubernetes.io/is-default-class"


class DefaultStorageClass(AdmissionPlugin):
    """Assign the cluster's default StorageClass to claims that name
    none (reference ``plugin/pkg/admission/storage/storageclass/
    setdefault/admission.go`` — default-enabled upstream): a PVC
    created with no class gets the class annotated
    ``storageclass.kubernetes.io/is-default-class``; with several
    marked default, the NEWEST wins (the reference's current
    tie-break)."""

    name = "DefaultStorageClass"

    def __init__(self, store=None):
        self.store = store

    def admit(self, req: AdmissionRequest) -> None:
        if self.store is None or req.kind != "PersistentVolumeClaim" \
                or req.operation != CREATE:
            return
        pvc = req.obj
        # only a NIL class is defaulted — an explicit "" is the user
        # asking for classless static provisioning (upstream semantics)
        if pvc.storage_class_name is not None:
            return
        defaults = [
            sc for sc in self.store.list_storage_classes()
            if sc.metadata.annotations.get(
                IS_DEFAULT_CLASS_ANNOTATION) == "true"
        ]
        if not defaults:
            return
        newest = max(defaults,
                     key=lambda sc: sc.metadata.creation_timestamp)
        pvc.storage_class_name = newest.name


POD_NODE_SELECTOR_ANNOTATION = "scheduler.alpha.kubernetes.io/node-selector"


class PodNodeSelector(AdmissionPlugin):
    """Merge a namespace-level node selector into every pod (reference
    ``plugin/pkg/admission/podnodeselector/admission.go``): the
    namespace annotation ``scheduler.alpha.kubernetes.io/node-selector``
    ("k=v,k2=v2") confines the namespace's pods to matching nodes; a
    pod whose own selector CONFLICTS with the namespace's is
    rejected."""

    name = "PodNodeSelector"

    def __init__(self, store=None):
        self.store = store

    @staticmethod
    def _parse(ann: str) -> Dict[str, str]:
        out = {}
        for part in ann.split(","):
            part = part.strip()
            if part and "=" in part:
                k, _, v = part.partition("=")
                out[k.strip()] = v.strip()
        return out

    def admit(self, req: AdmissionRequest) -> None:
        if self.store is None or req.kind != "Pod" or \
                req.operation != CREATE:
            return
        ns = self.store.get_namespace(req.namespace)
        if ns is None:
            return
        ann = ns.metadata.annotations.get(POD_NODE_SELECTOR_ANNOTATION)
        if not ann:
            return
        selector = self._parse(ann)
        pod: Pod = req.obj
        for k, v in selector.items():
            have = pod.spec.node_selector.get(k)
            if have is not None and have != v:
                raise AdmissionError(
                    f"pod node selector {k}={have!r} conflicts with "
                    f"namespace selector {k}={v!r}"
                )
            pod.spec.node_selector[k] = v


MIRROR_POD_ANNOTATION = "kubernetes.io/config.mirror"


class NodeRestriction(AdmissionPlugin):
    """Node identity confinement (reference ``plugin/pkg/admission/
    noderestriction/admission.go:79 Admit``): a kubelet authenticating
    as ``system:node:<name>`` may only touch its OWN Node object and
    pods BOUND to it — node A's credentials patching node B (or B's
    pods) is exactly the lateral movement this plugin exists to stop.
    Creates of regular pods by node identities are rejected; mirror
    pods (``kubernetes.io/config.mirror`` annotation) are allowed only
    on the node itself."""

    name = "NodeRestriction"

    PREFIX = "system:node:"

    def validate(self, req: AdmissionRequest) -> None:
        user = req.user or ""
        if not user.startswith(self.PREFIX):
            return
        node_name = user[len(self.PREFIX):]
        if req.kind == "Node":
            target = (req.obj or req.old_obj).metadata.name
            if target != node_name:
                raise AdmissionError(
                    f"node {node_name!r} is not allowed to modify node "
                    f"{target!r}"
                )
        elif req.kind == "Pod":
            if req.operation == CREATE:
                pod: Pod = req.obj
                if MIRROR_POD_ANNOTATION not in pod.metadata.annotations:
                    raise AdmissionError(
                        f"node {node_name!r} may only create mirror pods"
                    )
                if pod.spec.node_name != node_name:
                    raise AdmissionError(
                        f"node {node_name!r} may only create mirror pods "
                        f"bound to itself"
                    )
                return
            bound = (req.old_obj or req.obj).spec.node_name
            if bound != node_name:
                raise AdmissionError(
                    f"node {node_name!r} is not allowed to modify pods "
                    f"bound to node {bound!r}"
                )


@dataclass
class AdmissionChain:
    """Ordered plugin chain: all mutating passes, then all validating
    passes (reference admission.NewChainHandler ordering)."""

    plugins: List[AdmissionPlugin] = field(default_factory=list)

    @classmethod
    def default(cls) -> "AdmissionChain":
        return cls(
            [
                NamespaceLifecycle(),
                DefaultTolerationSeconds(),
                LimitRanger(),
                PodPriorityResolver(),
            ]
        )

    def run(self, req: AdmissionRequest) -> Any:
        ran: List[AdmissionPlugin] = []
        try:
            for p in self.plugins:
                p.admit(req)
                ran.append(p)
            for p in self.plugins:
                p.validate(req)
                if p not in ran:
                    ran.append(p)
        except Exception:
            # a later plugin rejected after earlier ones took side
            # effects (e.g. the quota plugin's in-flight charge):
            # unwind them NOW instead of letting a 30s TTL hold the
            # headroom hostage (upstream's quota evaluator is
            # transactional for the same reason)
            self.rollback(req, ran)
            raise
        return req.obj

    def validate_only(self, req: AdmissionRequest) -> None:
        """Run just the validating passes — the DELETE path's admission
        (the reference dispatches DELETE through validating admission;
        there is no object body to mutate)."""
        for p in self.plugins:
            p.validate(req)

    def rollback(self, req: AdmissionRequest,
                 plugins: Optional[List[AdmissionPlugin]] = None) -> None:
        """Undo admission side effects after a downstream failure (a
        later plugin's rejection, a store conflict, an allocator
        error). Safe to call for requests with no side effects."""
        for p in (plugins if plugins is not None else self.plugins):
            hook = getattr(p, "rollback", None)
            if hook is not None:
                try:
                    hook(req)
                except Exception:  # noqa: BLE001 — unwind must not mask
                    pass
