"""Service VIP dataplane: the proxier.

Behavioral equivalent of the reference's kube-proxy iptables/ipvs modes
(``pkg/proxy/iptables/proxier.go:257``, ``pkg/proxy/ipvs/proxier.go:342``):
watch Services and Endpoints, accumulate deltas in change trackers
(``pkg/proxy/service.go`` ServiceChangeTracker / ``endpoints.go``
EndpointsChangeTracker), and on each sync pass rebuild the kernel ruleset
atomically (``syncProxyRules``). Here "the kernel" is an in-memory rule
table: VIP:port → backend list, with round-robin (iptables random mode's
deterministic recast) and ClientIP session affinity. ``route()`` is the
dataplane lookup a connection would take.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import Endpoints, Service
from kubernetes_tpu.apiserver.store import ADDED, DELETED, MODIFIED, ClusterStore, Event


@dataclass
class Rule:
    """One VIP:port → backends chain (an iptables KUBE-SVC-* chain)."""

    service: str                     # "ns/name"
    cluster_ip: str
    port: int
    protocol: str
    backends: List[str] = field(default_factory=list)  # "ip:port"
    session_affinity: str = "None"   # or "ClientIP"


class Proxier:
    """One per node. ``sync()`` is cheap and idempotent: it rebuilds the
    table from tracked state only when something changed."""

    def __init__(self, store: ClusterStore, node_name: str = ""):
        self.store = store
        self.node_name = node_name
        self._lock = threading.Lock()
        self._services: Dict[str, Service] = {}
        self._endpoints: Dict[str, Endpoints] = {}
        self._rules: Dict[Tuple[str, int], Rule] = {}
        self._rr_state: Dict[Tuple[str, int], int] = {}
        self._affinity: Dict[Tuple[str, int, str], str] = {}
        self._dirty = True
        self._watch = None
        self.syncs = 0  # observability: how many rule rebuilds ran

    # -- wiring --------------------------------------------------------
    def start(self) -> "Proxier":
        with self._lock:
            for svc in self.store.list_all_services():
                self._services[f"{svc.metadata.namespace}/{svc.name}"] = svc
            for ep in self.store.list_endpoints():
                self._endpoints[f"{ep.namespace}/{ep.name}"] = ep
            self._dirty = True
        self._watch = self.store.watch(self._on_event)
        self.sync()
        return self

    def stop(self) -> None:
        if self._watch is not None:
            self._watch.stop()

    def _on_event(self, event: Event) -> None:
        if event.kind == "Service":
            key = f"{event.obj.metadata.namespace}/{event.obj.metadata.name}"
            with self._lock:
                if event.type == DELETED:
                    self._services.pop(key, None)
                else:
                    self._services[key] = event.obj
                self._dirty = True
        elif event.kind == "Endpoints":
            key = f"{event.obj.namespace}/{event.obj.name}"
            with self._lock:
                if event.type == DELETED:
                    self._endpoints.pop(key, None)
                else:
                    self._endpoints[key] = event.obj
                self._dirty = True

    # -- rule build (syncProxyRules) -----------------------------------
    def sync(self) -> None:
        with self._lock:
            if not self._dirty:
                return
            rules: Dict[Tuple[str, int], Rule] = {}
            for key, svc in self._services.items():
                if not svc.cluster_ip:
                    continue
                ep = self._endpoints.get(key)
                for sp in svc.ports:
                    target = sp.target_port or sp.port
                    backends = []
                    if ep is not None:
                        for addr in ep.addresses:
                            backends.append(f"{addr.ip}:{target}")
                    rules[(svc.cluster_ip, sp.port)] = Rule(
                        service=key,
                        cluster_ip=svc.cluster_ip,
                        port=sp.port,
                        protocol=sp.protocol,
                        backends=backends,
                        session_affinity=getattr(svc, "session_affinity", "None"),
                    )
            self._rules = rules
            # drop affinity entries for vanished VIPs/backends
            self._affinity = {
                k: b for k, b in self._affinity.items()
                if (k[0], k[1]) in rules and b in rules[(k[0], k[1])].backends
            }
            self._dirty = False
            self.syncs += 1

    # -- dataplane -----------------------------------------------------
    def route(self, cluster_ip: str, port: int,
              client_ip: str = "") -> Optional[str]:
        """Resolve a VIP connection to a backend ("ip:port"), honoring
        session affinity; None when no endpoints (iptables REJECT)."""
        self.sync()
        with self._lock:
            rule = self._rules.get((cluster_ip, port))
            if rule is None or not rule.backends:
                return None
            if rule.session_affinity == "ClientIP" and client_ip:
                akey = (cluster_ip, port, client_ip)
                backend = self._affinity.get(akey)
                if backend in rule.backends:
                    return backend
            idx = self._rr_state.get((cluster_ip, port), 0)
            backend = rule.backends[idx % len(rule.backends)]
            self._rr_state[(cluster_ip, port)] = idx + 1
            if rule.session_affinity == "ClientIP" and client_ip:
                self._affinity[(cluster_ip, port, client_ip)] = backend
            return backend

    def rules(self) -> List[Rule]:
        self.sync()
        with self._lock:
            return list(self._rules.values())


# ----------------------------------------------------------------------
def render_iptables(rules: List[Rule]) -> str:
    """Render the rule table as an iptables-restore ruleset — the exact
    artifact the reference's ``syncProxyRules`` writes through
    ``utiliptables.RestoreAll`` (``pkg/proxy/iptables/proxier.go:257``
    onward, writeLine buffers): a KUBE-SERVICES entry chain, one
    KUBE-SVC-* chain per VIP:port fanning out with
    ``statistic --mode random --probability 1/k`` matches, and one
    KUBE-SEP-* DNAT chain per backend. On a real Linux node this text
    pipes straight into ``iptables-restore --noflush``; in this harness
    it is the dataplane's canonical serialized form (tested, diffable,
    and byte-stable for a given rule table).
    """
    import hashlib

    def chain_hash(*parts: str) -> str:
        # KUBE-SVC-XXXXXXXXXXXXXXXX: 16-char base32-ish hash like
        # servicePortChainName (pkg/proxy/iptables/proxier.go:658)
        digest = hashlib.sha256("/".join(parts).encode()).hexdigest()
        return digest[:16].upper()

    nat_lines = ["*nat", ":KUBE-SERVICES - [0:0]"]
    # no-endpoints REJECTs live in the FILTER table — REJECT is invalid
    # in nat and would abort the whole iptables-restore COMMIT
    # (reference: proxier.go writes them into filterRules)
    filter_lines = ["*filter", ":KUBE-SERVICES - [0:0]"]
    svc_chains = []
    sep_chains = []
    svc_rules = []
    sep_rules = []
    reject_rules = []
    for rule in sorted(rules, key=lambda r: (r.service, r.port)):
        svc_chain = f"KUBE-SVC-{chain_hash(rule.service, str(rule.port))}"
        proto = rule.protocol.lower() or "tcp"
        n = len(rule.backends)
        if n == 0:
            reject_rules.append(
                f'-A KUBE-SERVICES -d {rule.cluster_ip}/32 -p {proto} '
                f'-m {proto} --dport {rule.port} '
                f'-m comment --comment "{rule.service} has no endpoints" '
                f"-j REJECT"
            )
            continue
        svc_chains.append(f":{svc_chain} - [0:0]")
        svc_rules.append(
            f'-A KUBE-SERVICES -d {rule.cluster_ip}/32 -p {proto} '
            f'-m {proto} --dport {rule.port} '
            f'-m comment --comment "{rule.service} cluster IP" '
            f"-j {svc_chain}"
        )
        sep_names = [
            f"KUBE-SEP-{chain_hash(rule.service, str(rule.port), backend)}"
            for backend in rule.backends
        ]
        if rule.session_affinity == "ClientIP":
            # returning sticky clients jump straight to THEIR endpoint
            # chain (per-SEP recent list, proxier.go writeSessionAffinity)
            for sep_chain in sep_names:
                svc_rules.append(
                    f"-A {svc_chain} -m recent --name {sep_chain} "
                    f"--rcheck --seconds 10800 --reap -j {sep_chain}"
                )
        for i, (backend, sep_chain) in enumerate(
            zip(rule.backends, sep_names)
        ):
            sep_chains.append(f":{sep_chain} - [0:0]")
            remaining = n - i
            if remaining > 1:
                svc_rules.append(
                    f"-A {svc_chain} -m statistic --mode random "
                    f"--probability {1.0 / remaining:.5f} -j {sep_chain}"
                )
            else:
                svc_rules.append(f"-A {svc_chain} -j {sep_chain}")
            if rule.session_affinity == "ClientIP":
                sep_rules.append(
                    f"-A {sep_chain} -m recent --name {sep_chain} --set "
                    f"-p {proto} -m {proto} -j DNAT "
                    f"--to-destination {backend}"
                )
            else:
                sep_rules.append(
                    f"-A {sep_chain} -p {proto} -m {proto} -j DNAT "
                    f"--to-destination {backend}"
                )
    nat_lines += svc_chains + sep_chains + svc_rules + sep_rules
    nat_lines.append("COMMIT")
    filter_lines += reject_rules
    filter_lines.append("COMMIT")
    return "\n".join(nat_lines + filter_lines) + "\n"
