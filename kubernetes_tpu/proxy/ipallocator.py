"""Service cluster-IP allocation.

Behavioral equivalent of the reference's service IP allocator
(``pkg/registry/core/service/ipallocator/allocator.go``): a bitmap over a
CIDR-sized range handing out VIPs, with explicit reserve (for a
user-specified clusterIP) and release on service deletion.
"""

from __future__ import annotations

import ipaddress
import threading
from typing import Optional


class IPAllocatorFull(Exception):
    pass


class IPAllocator:
    def __init__(self, cidr: str = "10.96.0.0/16"):
        self._net = ipaddress.ip_network(cidr)
        # skip network + first (apiserver VIP) + broadcast, like upstream
        self._base = int(self._net.network_address) + 2
        self._size = self._net.num_addresses - 3
        self._used: set = set()
        self._next = 0
        self._lock = threading.Lock()

    def allocate(self) -> str:
        with self._lock:
            if len(self._used) >= self._size:
                raise IPAllocatorFull(f"range {self._net} exhausted")
            for probe in range(self._size):
                off = (self._next + probe) % self._size
                if off not in self._used:
                    self._used.add(off)
                    self._next = off + 1
                    return str(ipaddress.ip_address(self._base + off))
            raise IPAllocatorFull(f"range {self._net} exhausted")

    def reserve(self, ip: str) -> bool:
        with self._lock:
            off = int(ipaddress.ip_address(ip)) - self._base
            if off < 0 or off >= self._size or off in self._used:
                return False
            self._used.add(off)
            return True

    def release(self, ip: str) -> None:
        with self._lock:
            off = int(ipaddress.ip_address(ip)) - self._base
            self._used.discard(off)

    def in_use(self) -> int:
        with self._lock:
            return len(self._used)
