"""ipvs-mode kube-proxy: the virtual IPVS table.

Behavioral equivalent of the reference's ipvs proxier
(``pkg/proxy/ipvs/proxier.go:342 NewProxier`` + ``graceful_termination
.go``): the SAME Service/Endpoints change trackers as the iptables mode
(the reference shares ``pkg/proxy/{service,endpoints}.go`` between
modes — here the inner ``Proxier`` plays that role), but the dataplane
is an in-memory IPVS state machine instead of an iptables ruleset:

- one **virtual server** per VIP:port:protocol, each holding weighted
  **real servers** (the endpoints);
- **scheduling algorithms**: ``rr`` (round robin) and ``lc`` (least
  connection — picks the real server with the fewest active
  connections per weight), selectable like ``--ipvs-scheduler``;
- **session persistence** for ClientIP affinity (IPVS persistence
  timeout rather than iptables ``recent`` matches);
- **graceful termination**: a real server whose endpoint vanished gets
  weight 0 — new connections skip it, existing connections drain, and
  the entry is deleted only when its active-connection count reaches
  zero (``graceful_termination.go`` gracefulDeleteRS).

``connect()`` models a connection (incrementing the active count the
``lc`` scheduler and the drain logic consume); ``route()`` is the
stateless lookup. Both resolve exactly like a kernel IPVS director
would on a real node.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.proxy.proxier import Proxier

# reference default for ClientIP affinity (v1.DefaultClientIPServiceAffinitySeconds)
DEFAULT_PERSISTENCE_SECONDS = 10800.0


@dataclass
class RealServer:
    address: str                 # "ip:port"
    weight: int = 1              # 0 = draining (graceful termination)
    active_conns: int = 0


@dataclass
class VirtualServer:
    vip: str
    port: int
    protocol: str = "TCP"
    scheduler: str = "rr"
    persistence_timeout: float = 0.0
    reals: Dict[str, RealServer] = field(default_factory=dict)
    rr_idx: int = 0


class Connection:
    """One routed connection; ``close()`` releases it (drives both the
    lc scheduler's counts and graceful-termination deletion)."""

    def __init__(self, proxier: "IpvsProxier", key: Tuple[str, int],
                 backend: str):
        self._proxier = proxier
        self._key = key
        self.backend = backend
        self._open = True

    def close(self) -> None:
        if self._open:
            self._open = False
            self._proxier._release(self._key, self.backend)


class IpvsProxier:
    """One per node, like the iptables-mode ``Proxier`` it wraps."""

    def __init__(self, store: ClusterStore, node_name: str = "",
                 scheduler: str = "rr"):
        if scheduler not in ("rr", "lc"):
            raise ValueError(f"unsupported ipvs scheduler {scheduler!r}")
        self._inner = Proxier(store, node_name)
        self.scheduler = scheduler
        self._lock = threading.Lock()
        self._servers: Dict[Tuple[str, int], VirtualServer] = {}
        # (vip, port, client) -> (backend, stamp)
        self._persist: Dict[Tuple[str, int, str], Tuple[str, float]] = {}
        self.syncs = 0
        # rebuild only when the inner trackers actually rebuilt: every
        # route()/connect() calls sync(), which must be O(1) when the
        # service/endpoints world is unchanged
        self._last_inner_syncs = -1

    # -- wiring --------------------------------------------------------
    def start(self) -> "IpvsProxier":
        self._inner.start()
        self.sync()
        return self

    def stop(self) -> None:
        self._inner.stop()

    # -- sync (syncProxyRules, ipvs flavor) ----------------------------
    def sync(self) -> None:
        rules = self._inner.rules()   # tracker-driven, cheap when clean
        with self._lock:
            if self._inner.syncs == self._last_inner_syncs:
                return                # table already current
            self._last_inner_syncs = self._inner.syncs
            seen = set()
            for rule in rules:
                key = (rule.cluster_ip, rule.port)
                seen.add(key)
                vs = self._servers.get(key)
                if vs is None:
                    vs = VirtualServer(
                        vip=rule.cluster_ip, port=rule.port,
                        protocol=rule.protocol,
                        scheduler=self.scheduler,
                    )
                    self._servers[key] = vs
                vs.persistence_timeout = (
                    DEFAULT_PERSISTENCE_SECONDS
                    if rule.session_affinity == "ClientIP" else 0.0
                )
                wanted = set(rule.backends)
                for addr in wanted:
                    rs = vs.reals.get(addr)
                    if rs is None:
                        vs.reals[addr] = RealServer(address=addr)
                    else:
                        rs.weight = 1       # endpoint came back mid-drain
                for addr, rs in list(vs.reals.items()):
                    if addr not in wanted:
                        # graceful termination: weight 0, delete only
                        # once drained
                        rs.weight = 0
                        if rs.active_conns == 0:
                            del vs.reals[addr]
            for key in list(self._servers):
                if key not in seen:
                    # whole service gone: its sessions die with it (the
                    # kernel flushes the virtual server)
                    del self._servers[key]
            now = time.monotonic()
            self._persist = {
                k: (backend, stamp)
                for k, (backend, stamp) in self._persist.items()
                if (k[0], k[1]) in self._servers
                # expired sessions must not accumulate for the
                # service's lifetime
                and now - stamp < self._servers[
                    (k[0], k[1])].persistence_timeout
            }
            self.syncs += 1

    # -- scheduling ----------------------------------------------------
    def _pick(self, vs: VirtualServer, client_ip: str,
              now: float) -> Optional[str]:
        if vs.persistence_timeout > 0 and client_ip:
            got = self._persist.get((vs.vip, vs.port, client_ip))
            if got is not None:
                backend, stamp = got
                # a draining (weight-0) real server keeps its persistent
                # sessions until the timeout — that IS the drain
                if backend in vs.reals and \
                        now - stamp < vs.persistence_timeout:
                    self._persist[(vs.vip, vs.port, client_ip)] = (
                        backend, now)
                    return backend
        candidates = sorted(
            (rs for rs in vs.reals.values() if rs.weight > 0),
            key=lambda rs: rs.address,
        )
        if not candidates:
            return None
        if vs.scheduler == "lc":
            backend = min(
                candidates,
                key=lambda rs: (rs.active_conns / rs.weight, rs.address),
            ).address
        else:                       # rr
            backend = candidates[vs.rr_idx % len(candidates)].address
            vs.rr_idx += 1
        if vs.persistence_timeout > 0 and client_ip:
            self._persist[(vs.vip, vs.port, client_ip)] = (backend, now)
        return backend

    # -- dataplane -----------------------------------------------------
    def route(self, vip: str, port: int,
              client_ip: str = "") -> Optional[str]:
        """Stateless lookup: backend "ip:port" or None (no virtual
        server / no live real server — the kernel would REJECT)."""
        self.sync()
        with self._lock:
            vs = self._servers.get((vip, port))
            if vs is None:
                return None
            return self._pick(vs, client_ip, time.monotonic())

    def connect(self, vip: str, port: int,
                client_ip: str = "") -> Optional[Connection]:
        """Routed connection holding an active-conn slot until
        ``close()``."""
        self.sync()
        with self._lock:
            vs = self._servers.get((vip, port))
            if vs is None:
                return None
            backend = self._pick(vs, client_ip, time.monotonic())
            if backend is None:
                return None
            vs.reals[backend].active_conns += 1
            return Connection(self, (vip, port), backend)

    def _release(self, key: Tuple[str, int], backend: str) -> None:
        with self._lock:
            vs = self._servers.get(key)
            if vs is None:
                return
            rs = vs.reals.get(backend)
            if rs is None:
                return
            rs.active_conns = max(0, rs.active_conns - 1)
            if rs.weight == 0 and rs.active_conns == 0:
                del vs.reals[backend]     # drain complete

    # -- introspection (ipvsadm -L -n analog) --------------------------
    def virtual_servers(self) -> List[VirtualServer]:
        self.sync()
        with self._lock:
            return [
                VirtualServer(
                    vip=vs.vip, port=vs.port, protocol=vs.protocol,
                    scheduler=vs.scheduler,
                    persistence_timeout=vs.persistence_timeout,
                    reals={
                        a: RealServer(r.address, r.weight, r.active_conns)
                        for a, r in vs.reals.items()
                    },
                    rr_idx=vs.rr_idx,
                )
                for vs in self._servers.values()
            ]
