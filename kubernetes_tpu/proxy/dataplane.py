"""Virtual dataplane: EXECUTES the rendered iptables-restore ruleset.

The reference's kube-proxy ends at ``iptables-restore`` — the kernel
executes the rules. This module is that kernel half for the in-process
framework (closing VERDICT r2 missing #7, "renders but nothing
executes it"): ``VirtualDataplane.load`` parses the exact text
``render_iptables`` emits (chains, jumps, DNAT targets, statistic
random matches, recent-module session affinity, filter REJECTs) and
``route`` walks a synthetic connection through the loaded tables the
way netfilter would — so tests prove the rendered ARTIFACT behaves,
not merely that it diffs cleanly.

Semantics carried over from the matched extensions:
- ``-m statistic --mode random --probability p``: each rule matches
  with probability p (deterministic via an injectable RNG),
- ``-m recent --name X --set`` / ``--rcheck --seconds S --reap``:
  per-chain source-IP recency lists with expiry — ClientIP affinity,
- filter-table ``REJECT``: connections to endpoint-less VIPs are
  refused (reference: REJECT lives in *filter; nat chains DNAT).
"""

from __future__ import annotations

import random
import re
import time
from typing import Dict, List, Optional, Tuple


class _NatRule:
    __slots__ = ("dest", "proto", "dport", "probability", "jump",
                 "dnat_to", "recent_set", "recent_check",
                 "recent_seconds")

    def __init__(self):
        self.dest: Optional[str] = None
        self.proto: Optional[str] = None
        self.dport: Optional[int] = None
        self.probability: Optional[float] = None
        self.jump: Optional[str] = None
        self.dnat_to: Optional[str] = None
        self.recent_set: Optional[str] = None
        self.recent_check: Optional[str] = None
        self.recent_seconds: float = 0.0


_TOKEN_RULES = (
    ("dest", re.compile(r"-d (\S+?)/32")),
    ("proto", re.compile(r"-p (\w+)")),
    ("dport", re.compile(r"--dport (\d+)")),
    ("probability", re.compile(r"--probability ([\d.]+)")),
    ("dnat_to", re.compile(r"-j DNAT --to-destination (\S+)")),
)


class VirtualDataplane:
    """Parses and executes the proxier's iptables-restore text."""

    def __init__(self, rng: Optional[random.Random] = None,
                 clock=time.monotonic):
        self._nat: Dict[str, List[_NatRule]] = {}
        self._filter_rejects: List[_NatRule] = []
        # recent-module lists: name -> {src_ip: last_seen}
        self._recent: Dict[str, Dict[str, float]] = {}
        self._rng = rng or random.Random(0)
        self._clock = clock

    # -- loading -------------------------------------------------------
    def load(self, ruleset: str) -> None:
        """iptables-restore semantics: *table sections, ``:CHAIN``
        declarations flush/create the chain, ``-A`` appends, COMMIT
        applies. Re-loading replaces declared chains atomically."""
        table = ""
        nat: Dict[str, List[_NatRule]] = {}
        rejects: List[_NatRule] = []
        for raw in ruleset.splitlines():
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if line.startswith("*"):
                table = line[1:]
                continue
            if line == "COMMIT":
                continue
            if line.startswith(":"):
                chain = line[1:].split()[0]
                if table == "nat":
                    nat.setdefault(chain, [])
                continue
            if not line.startswith("-A "):
                raise ValueError(f"unsupported iptables line: {line!r}")
            chain, rest = line[3:].split(" ", 1)
            rule = self._parse_rule(rest)
            if table == "filter":
                if "-j REJECT" in rest:
                    rejects.append(rule)
                continue
            nat.setdefault(chain, []).append(rule)
        self._nat = nat
        self._filter_rejects = rejects

    @staticmethod
    def _parse_rule(rest: str) -> "_NatRule":
        rule = _NatRule()
        for attr, rx in _TOKEN_RULES:
            m = rx.search(rest)
            if m:
                val = m.group(1)
                if attr == "dport":
                    val = int(val)
                elif attr == "probability":
                    val = float(val)
                setattr(rule, attr, val)
        m = re.search(r"-m recent --name (\S+) --set", rest)
        if m:
            rule.recent_set = m.group(1)
        m = re.search(
            r"-m recent --name (\S+) --rcheck --seconds ([\d.]+)", rest
        )
        if m:
            rule.recent_check = m.group(1)
            rule.recent_seconds = float(m.group(2))
        if rule.dnat_to is None:
            m = re.search(r"-j (\S+)$", rest)
            if m and m.group(1) not in ("REJECT", "DNAT"):
                rule.jump = m.group(1)
        return rule

    # -- execution -----------------------------------------------------
    def route(self, dst_ip: str, dport: int, src_ip: str = "",
              proto: str = "tcp") -> Optional[str]:
        """One connection through the tables: returns the DNAT'd
        "ip:port" backend, or None (rejected / no rule — the kernel
        would REJECT or fall through to routing)."""
        now = self._clock()
        for rej in self._filter_rejects:
            if rej.dest == dst_ip and rej.dport == dport and (
                    rej.proto in (None, proto)):
                return None
        return self._walk("KUBE-SERVICES", dst_ip, dport, src_ip,
                          proto, now, depth=0)

    def _walk(self, chain: str, dst_ip: str, dport: int, src_ip: str,
              proto: str, now: float, depth: int) -> Optional[str]:
        if depth > 16:  # netfilter's own chain-jump guard
            return None
        for rule in self._nat.get(chain, ()):
            if rule.dest is not None and rule.dest != dst_ip:
                continue
            if rule.dport is not None and rule.dport != dport:
                continue
            if rule.proto is not None and rule.proto != proto:
                continue
            if rule.recent_check is not None:
                seen = self._recent.get(rule.recent_check, {}).get(src_ip)
                if seen is None or now - seen > rule.recent_seconds:
                    continue  # not recent (or reaped): no match
            if rule.probability is not None and \
                    self._rng.random() >= rule.probability:
                continue
            if rule.recent_set is not None:
                self._recent.setdefault(rule.recent_set, {})[src_ip] = now
            if rule.dnat_to is not None:
                return rule.dnat_to
            if rule.jump is not None:
                out = self._walk(rule.jump, dst_ip, dport, src_ip,
                                 proto, now, depth + 1)
                if out is not None:
                    return out
        return None
