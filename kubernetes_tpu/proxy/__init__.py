from kubernetes_tpu.proxy.ipallocator import IPAllocator, IPAllocatorFull
from kubernetes_tpu.proxy.proxier import Proxier, Rule
