from kubernetes_tpu.proxy.dataplane import VirtualDataplane
from kubernetes_tpu.proxy.ipallocator import IPAllocator, IPAllocatorFull
from kubernetes_tpu.proxy.ipvs import IpvsProxier
from kubernetes_tpu.proxy.proxier import Proxier, Rule
