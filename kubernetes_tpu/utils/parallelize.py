"""Chunked parallel fan-out (reference
``internal/parallelize/parallelism.go:27,44-58``): 16 workers by default,
chunk size ``max(1, min(sqrt(n), n/parallelism+1))``.

On the host path this exists for capability parity and for IO-bound work
(extender calls); the compute-bound per-node loops the reference fans out
with this are replaced wholesale by the device batch path
(``kubernetes_tpu.ops``), which is the point of the TPU build.
"""

from __future__ import annotations

import math
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional

DEFAULT_PARALLELISM = 16


class Parallelizer:
    def __init__(self, parallelism: int = DEFAULT_PARALLELISM):
        self.parallelism = max(1, parallelism)
        self._pool: Optional[ThreadPoolExecutor] = None

    def _ensure_pool(self) -> ThreadPoolExecutor:
        if self._pool is None:
            self._pool = ThreadPoolExecutor(max_workers=self.parallelism)
        return self._pool

    def chunk_size(self, n: int) -> int:
        return max(1, min(int(math.sqrt(n)), n // self.parallelism + 1))

    def until(self, n: int, fn: Callable[[int], None],
              stop_check: Optional[Callable[[], bool]] = None) -> None:
        """Run fn(i) for i in [0, n). Honors an optional early-cancel
        predicate between chunks (the reference cancels via ctx when enough
        feasible nodes are found)."""
        if n <= 0:
            return
        if self.parallelism == 1 or n == 1:
            for i in range(n):
                if stop_check is not None and stop_check():
                    return
                fn(i)
            return
        chunk = self.chunk_size(n)
        pool = self._ensure_pool()

        def run_chunk(start: int) -> None:
            for i in range(start, min(start + chunk, n)):
                if stop_check is not None and stop_check():
                    return
                fn(i)

        futures = [pool.submit(run_chunk, s) for s in range(0, n, chunk)]
        for f in futures:
            f.result()
