"""Injectable clocks (reference k8s.io/apimachinery/pkg/util/clock), so
queue backoff and cache TTL tests are deterministic
(``scheduling_queue.go:161 WithClock`` carry-over)."""

from __future__ import annotations

import threading
import time


class RealClock:
    def now(self) -> float:
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)


class FakeClock:
    def __init__(self, start: float = 0.0):
        self._now = start
        self._lock = threading.Lock()

    def now(self) -> float:
        with self._lock:
            return self._now

    def step(self, seconds: float) -> None:
        with self._lock:
            self._now += seconds

    def sleep(self, seconds: float) -> None:
        self.step(seconds)
