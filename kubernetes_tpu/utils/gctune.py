"""Cyclic-GC tuning for throughput phases.

Python's generational GC scans every tracked container object; at the
benchmark scale (30k pods × ~20 API objects each) the default gen-0
threshold of 700 allocations makes collection dominate pod admission
(~17µs of the ~22µs/pod parse cost, measured). The API object graph is
acyclic — dataclasses holding dicts/lists with no back-references — so
reference counting alone reclaims it; the cyclic collector only needs to
run rarely (cycles still arise from tracebacks, closures, etc.).

This is the moral equivalent of GOGC tuning on the reference's Go
components: the collector stays ON, it just stops scanning the
steady-state heap on every micro-allocation burst.
"""

from __future__ import annotations

import gc

_TUNED = False


def tune_for_throughput(freeze: bool = True) -> None:
    """Raise GC thresholds (and optionally freeze the current heap out
    of scanning). Call once after process setup, before a sustained
    allocation-heavy phase (the perf harness and bench entry do)."""
    global _TUNED
    if _TUNED:
        return
    if freeze:
        gc.collect()
        gc.freeze()
    gc.set_threshold(100_000, 100, 100)
    _TUNED = True


def is_tuned() -> bool:
    return _TUNED
