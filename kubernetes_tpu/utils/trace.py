"""Step-span tracing (re-implementation of the vendored
``k8s.io/utils/trace`` used at ``generic_scheduler.go:98-104``): spans with
steps, logged only when total duration exceeds a threshold."""

from __future__ import annotations

import logging
import time
from typing import List, Optional, Tuple

logger = logging.getLogger("kubernetes_tpu.trace")


class Trace:
    def __init__(self, name: str, **fields):
        self.name = name
        self.fields = fields
        self.start = time.monotonic()
        self.steps: List[Tuple[float, str]] = []
        self._logged = False

    def step(self, msg: str) -> None:
        self.steps.append((time.monotonic(), msg))

    def log_if_long(self, threshold: float) -> None:
        total = time.monotonic() - self.start
        if total < threshold:
            return
        self._logged = True
        parts = [f'"{self.name}" {self.fields} total={total * 1000:.1f}ms']
        prev = self.start
        for ts, msg in self.steps:
            parts.append(f"  step {msg}: +{(ts - prev) * 1000:.1f}ms")
            prev = ts
        logger.info("\n".join(parts))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
