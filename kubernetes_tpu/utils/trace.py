"""Step-span tracing (re-implementation of the vendored
``k8s.io/utils/trace`` used at ``generic_scheduler.go:98-104``): spans with
steps, logged only when total duration exceeds a threshold.

Since the observability layer landed this is a thin compat shim over
``kubernetes_tpu.observability.Tracer``: every ``Trace`` records a real
span (with its steps as instant events) into the flight recorder, so
``log_if_long`` callers keep their threshold-gated log line AND the same
data shows up in ``/debug/trace`` Perfetto dumps.

Step-delta fix: steps are sorted by timestamp before deltas are
computed. Helper code can append steps out of order (a sub-call stamped
its step before the caller stamped an earlier one), and the old
previous-APPENDED-step accounting then reported negative or wildly
inflated deltas after long gaps; chronological order is the only
ordering under which "+Nms" is the true time between adjacent steps.
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional, Tuple

logger = logging.getLogger("kubernetes_tpu.trace")


class Trace:
    def __init__(self, name: str, **fields):
        self.name = name
        self.fields = fields
        self.start = time.monotonic()
        self.steps: List[Tuple[float, str]] = []
        self._logged = False
        self._recorded = False

    def step(self, msg: str) -> None:
        self.steps.append((time.monotonic(), msg))

    def _record_span(self, end: float) -> None:
        """Fold this trace onto the flight recorder (once). Pod-scoped
        traces key by UID — the same trace id every other hop uses, so
        the serial scheduling span stitches into the pod's causal trace
        — and are HEAD-SAMPLED like every other per-pod span (the serial
        path creates a Trace per pod; unsampled recording would flood
        the ring and take the histogram lock per pod). Traces with no
        uid (rare, not per-pod) record unconditionally."""
        if self._recorded:
            return
        self._recorded = True
        try:
            from kubernetes_tpu.observability import get_tracer

            tracer = get_tracer()
            if not tracer.enabled:
                return
            uid = str(self.fields.get("uid", ""))
            if uid and not tracer.sampled(uid):
                return
            tracer.record(f"trace.{self.name}", self.start, end,
                          trace=uid, steps=len(self.steps),
                          pod=str(self.fields.get("pod", "")))
            for ts, msg in self.steps:
                tracer.event(f"step.{msg}", trace=uid, at_mono=ts)
        except Exception:   # pragma: no cover — shim must never raise
            pass

    def log_if_long(self, threshold: float) -> None:
        now = time.monotonic()
        self._record_span(now)
        total = now - self.start
        if total < threshold:
            return
        self._logged = True
        parts = [f'"{self.name}" {self.fields} total={total * 1000:.1f}ms']
        prev = self.start
        # chronological order, not append order: deltas between adjacent
        # steps are only meaningful when the timestamps are sorted
        for ts, msg in sorted(self.steps):
            parts.append(f"  step {msg}: +{(ts - prev) * 1000:.1f}ms")
            prev = ts
        logger.info("\n".join(parts))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._record_span(time.monotonic())
        return False
