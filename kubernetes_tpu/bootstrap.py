"""Cluster bootstrap — the kubeadm equivalent.

Behavioral equivalent of the reference's kubeadm (``cmd/kubeadm``): phased
bring-up of a working control plane — ``init`` starts the API server,
controller manager, and scheduler (with optional leader election), mints a
bootstrap token, and ``join`` attaches nodes (here: hollow kubelets) using
that token; ``reset`` tears everything down. The phases mirror kubeadm's
(``cmd/kubeadm/app/cmd/phases``): control-plane, token, node-join.

This is also the one-call test/demo entry: ``Cluster.up(nodes=5)`` gives a
full live cluster in-process.
"""

from __future__ import annotations

import secrets
import threading
from typing import Dict, List, Optional

from kubernetes_tpu.apiserver.rest import APIServer, RestClient
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.kubemark import HollowCluster, HollowNode
from kubernetes_tpu.scheduler.scheduler import Scheduler


class Cluster:
    """A whole cluster in one object: apiserver + kcm + scheduler +
    joined nodes."""

    def __init__(self):
        self.store: Optional[ClusterStore] = None
        self.apiserver: Optional[APIServer] = None
        self.controller_manager: Optional[ControllerManager] = None
        self.scheduler: Optional[Scheduler] = None
        self.nodes = None  # HollowCluster
        self.bootstrap_token: str = ""
        self.component_tokens: Dict[str, str] = {}
        self.pki: Dict[str, str] = {}
        self.kubeconfigs: Dict[str, Dict[str, str]] = {}
        self.preflight_warnings: List[str] = []
        # node name -> "cert:<fingerprint>" bearer credential minted by
        # the TLS bootstrap (kubeadm's kubelet.conf client cert analog)
        self.node_credentials: Dict[str, str] = {}
        self._up = False

    # -- phases (kubeadm init) -----------------------------------------
    def phase_control_plane(self, leader_elect: bool = False,
                            controllers: Optional[List[str]] = None,
                            rbac: bool = True) -> None:
        self.store = ClusterStore()
        authorizer = None
        if rbac:
            # default-on RBAC (reference kubeadm enables the RBAC
            # authorization mode by default): bootstrap roles/bindings
            # for the control-plane components + per-component tokens
            from kubernetes_tpu.apiserver.rbac import (
                provision_bootstrap_policy,
            )

            authorizer = provision_bootstrap_policy(self.store)
        self.apiserver = APIServer(
            store=self.store,
            **({"authorizer": authorizer} if authorizer else {}),
        ).start()
        if rbac:
            for component in ("kube-scheduler", "kube-controller-manager"):
                token = secrets.token_hex(12)
                self.apiserver.tokens[token] = f"system:{component}"
                self.component_tokens[component] = token
            admin_token = secrets.token_hex(12)
            self.apiserver.tokens[admin_token] = "admin"
            self.component_tokens["admin"] = admin_token
        self.controller_manager = ControllerManager(
            self.store, controllers=controllers, leader_elect=leader_elect
        )
        self.controller_manager.start()
        self.scheduler = Scheduler.create(self.store)
        if leader_elect:
            # leader_elect covers the scheduler too, not just the
            # controller manager (reference server.go:199-208)
            self.scheduler.run_with_leader_election()
        else:
            self.scheduler.run()

    def phase_bootstrap_token(self) -> str:
        """Mint a join token, registered with the apiserver's authn
        (kubeadm token create)."""
        token = f"{secrets.token_hex(3)}.{secrets.token_hex(8)}"
        self.apiserver.tokens[token] = "system:bootstrap:node"
        self.bootstrap_token = token
        return token

    def phase_join_nodes(self, count: int, token: str = "",
                         capacity: Optional[Dict[str, str]] = None,
                         tpu_chips: int = 0) -> List[HollowNode]:
        """kubeadm join: nodes authenticate with the bootstrap token,
        complete the TLS bootstrap (CSR → auto-approve → signed client
        cert → node identity credential), register, and heartbeat.
        Per-node credentials land in ``self.node_credentials`` as
        ``cert:<fingerprint>`` bearer tokens that authenticate as
        ``system:node:<name>`` (kubeadm's kubelet.conf analog)."""
        if token and token != self.bootstrap_token:
            raise PermissionError("invalid bootstrap token")
        if self.nodes is None:
            nlc = self.controller_manager.controllers.get("nodelifecycle")
            self.nodes = HollowCluster(
                self.store,
                heartbeat_fn=nlc.heartbeat if nlc is not None else None,
            )
        started = self.nodes.start_nodes(count, capacity=capacity,
                                         tpu_chips=tpu_chips)
        if token:
            for node in started:
                try:
                    self.node_credentials[node.name] = \
                        self.tls_bootstrap(node.name, token)
                except Exception:  # noqa: BLE001 — joining stays usable
                    # even when the CSR trio isn't running (subset
                    # controller configs); the credential is then absent
                    pass
        return started

    def tls_bootstrap(self, node_name: str, token: str,
                      timeout: float = 15.0) -> str:
        """The kubeadm TLS bootstrap, through the API: the bootstrap
        token submits a client CSR (subject CN=system:node:<name>), the
        csrapproving controller auto-approves it (bootstrap identity +
        kubelet client signer), csrsigning issues the certificate, and
        the certificate's fingerprint becomes the node's API credential
        (x509 authn stand-in — rest.py resolve_cert_fingerprint)."""
        import hashlib
        import time as _time

        from kubernetes_tpu.api.types import CertificateSigningRequest
        from kubernetes_tpu.controllers.certificates import (
            KUBE_APISERVER_CLIENT_KUBELET_SIGNER,
        )

        client = self.client(token)
        csr = CertificateSigningRequest(
            request=f"CN=system:node:{node_name},O=system:nodes",
            signer_name=KUBE_APISERVER_CLIENT_KUBELET_SIGNER,
            usages=["client auth"],
        )
        csr.metadata.name = f"node-csr-{node_name}"
        try:
            client.create(csr)
        except ValueError:
            pass   # rejoin: the CSR exists; wait for its certificate
        deadline = _time.time() + timeout
        while _time.time() < deadline:
            live = client.get("CertificateSigningRequest",
                              csr.metadata.name, namespace=None)
            if live is not None and live.certificate:
                fp = hashlib.sha256(live.certificate.encode()).hexdigest()
                return f"cert:{fp}"
            _time.sleep(0.05)
        raise TimeoutError(
            f"TLS bootstrap for {node_name}: CSR not signed in time")

    # -- additional init phases (cmd/kubeadm/app/cmd/phases/init) ------
    def phase_preflight(self) -> List[str]:
        """kubeadm init preflight: environment checks, returned as
        warnings (reference preflight.go runs ~30 system checks; the
        in-process analogs are the ones that can actually fail here)."""
        warnings: List[str] = []
        if self.apiserver is not None:
            warnings.append("control plane already running "
                            "(phase order: preflight precedes it)")
        try:
            import jax  # noqa: F401
        except Exception:  # pragma: no cover — jax is baked in
            warnings.append("jax unavailable: TPU batch path disabled")
        return warnings

    def phase_certs(self) -> Dict[str, str]:
        """kubeadm init certs: the cluster CA signs one client cert per
        control-plane component (reference certs.go writes the pki/
        tree; here the CSR machinery's CA issues, and the blobs are the
        pki dict — fingerprints of these authenticate like any
        CSR-issued cert once pushed through the CSR flow)."""
        from kubernetes_tpu.controllers.certificates import (
            KUBE_APISERVER_CLIENT_SIGNER,
            sign_request,
        )

        self.pki = {}
        for component in ("kube-apiserver", "kube-scheduler",
                          "kube-controller-manager", "admin"):
            subject = f"CN=system:{component},O=system:masters" \
                if component == "admin" \
                else f"CN=system:{component}"
            self.pki[component] = sign_request(
                subject, KUBE_APISERVER_CLIENT_SIGNER)
        return self.pki

    def phase_kubeconfig(self) -> Dict[str, Dict[str, str]]:
        """kubeadm init kubeconfig: one {server, token} credential
        record per component (admin.conf / scheduler.conf /
        controller-manager.conf analogs — reference kubeconfig.go)."""
        if self.apiserver is None:
            raise RuntimeError("kubeconfig phase needs the control plane")
        self.kubeconfigs = {
            name: {"server": self.apiserver.url, "token": tok}
            for name, tok in self.component_tokens.items()
        }
        return self.kubeconfigs

    def phase_wait_control_plane(self, timeout: float = 10.0) -> None:
        """kubeadm init wait-control-plane: poll /healthz until it
        answers (reference waitcontrolplane.go)."""
        import time as _time

        deadline = _time.time() + timeout
        client = RestClient(self.apiserver.url)
        while _time.time() < deadline:
            if client.healthz():
                return
            _time.sleep(0.05)
        raise TimeoutError("control plane not healthy in time")

    def phase_upload_config(self) -> None:
        """kubeadm init upload-config: the cluster configuration lands
        in the kubeadm-config ConfigMap in kube-system so later joins/
        upgrades read one source of truth (reference uploadconfig.go)."""
        from kubernetes_tpu.api.types import ConfigMap, ObjectMeta

        cm = ConfigMap(
            metadata=ObjectMeta(name="kubeadm-config",
                                namespace="kube-system"),
            data={
                "ClusterConfiguration": (
                    f"apiServer: {self.apiserver.url}\n"
                    f"controllers: "
                    f"{len(self.controller_manager.controllers)}\n"
                    "schedulerName: default-scheduler\n"
                ),
            },
        )
        try:
            self.store.create_object("ConfigMap", cm)
        except ValueError:
            self.store.update_object("ConfigMap", cm)

    def phase_mark_control_plane(
            self, name: str = "control-plane-0") -> None:
        """kubeadm init mark-control-plane: the control-plane node gets
        its role label and NoSchedule taint so workloads stay off it
        (reference markcontrolplane.go)."""
        from kubernetes_tpu.api.resource import parse_quantity
        from kubernetes_tpu.api.types import Node, NodeStatus, ObjectMeta, Taint

        caps = {"cpu": parse_quantity("4"),
                "memory": parse_quantity("8Gi"), "pods": parse_quantity("110")}
        node = Node(
            metadata=ObjectMeta(
                name=name,
                labels={"node-role.kubernetes.io/control-plane": ""},
            ),
            status=NodeStatus(capacity=dict(caps),
                              allocatable=dict(caps)),
        )
        node.spec.taints = [Taint(
            key="node-role.kubernetes.io/control-plane",
            effect="NoSchedule",
        )]
        if self.store.get_node(name) is not None:
            return   # idempotent: never clobber live node status
        self.store.add_node(node)
        # the real control-plane node's kubelet heartbeats; without one
        # the nodelifecycle controller would mark it NotReady after the
        # grace period and start evicting — heartbeat on its behalf
        nlc = self.controller_manager.controllers.get("nodelifecycle") \
            if self.controller_manager else None
        if nlc is not None:
            stop = threading.Event()
            self._cp_heartbeat_stop = stop

            def beat() -> None:
                while not stop.is_set():
                    try:
                        nlc.heartbeat(name)
                    except Exception:  # noqa: BLE001 — teardown races
                        pass
                    stop.wait(5.0)

            threading.Thread(target=beat, daemon=True,
                             name="cp-heartbeat").start()

    def phase_addons(self) -> None:
        """kubeadm init addons: kube-proxy as a DaemonSet (one pod per
        node, tolerating the control-plane taint) and CoreDNS as a
        2-replica Deployment + kube-dns ClusterIP Service — installed
        through the API and reconciled by THIS cluster's own
        controllers (reference addons.go applies the same two)."""
        from kubernetes_tpu.api.labels import LabelSelector
        from kubernetes_tpu.api.types import (
            DaemonSet,
            Deployment,
            ObjectMeta,
            Service,
            ServicePort,
        )

        proxy = DaemonSet(
            metadata=ObjectMeta(name="kube-proxy",
                                namespace="kube-system"),
            selector=LabelSelector(match_labels={"k8s-app": "kube-proxy"}),
            template={
                "metadata": {"labels": {"k8s-app": "kube-proxy"}},
                "spec": {
                    "containers": [{
                        "name": "kube-proxy", "image": "kube-proxy",
                        "resources": {"requests": {"cpu": "10m"}},
                    }],
                    # the reference kube-proxy manifest tolerates
                    # EVERYTHING (`- operator: Exists`) — control-plane
                    # NoSchedule and unreachable NoExecute alike
                    "tolerations": [{"operator": "Exists"}],
                },
            },
        )
        dns = Deployment(
            metadata=ObjectMeta(name="coredns", namespace="kube-system"),
            selector=LabelSelector(match_labels={"k8s-app": "kube-dns"}),
            replicas=2,
            template={
                "metadata": {"labels": {"k8s-app": "kube-dns"}},
                "spec": {"containers": [{
                    "name": "coredns", "image": "coredns",
                    "resources": {"requests": {"cpu": "100m",
                                               "memory": "70Mi"}},
                }]},
            },
        )
        svc = Service(
            metadata=ObjectMeta(name="kube-dns", namespace="kube-system"),
            selector={"k8s-app": "kube-dns"},
            ports=[ServicePort(name="dns", port=53, target_port=53)],
        )
        from kubernetes_tpu.apiserver.store import ConflictError

        client = self.client()
        for obj in (proxy, dns, svc):
            try:
                client.create(obj)
            except (ValueError, ConflictError):
                pass   # addon phase is idempotent (409 AlreadyExists)

    # -- porcelain ------------------------------------------------------
    @classmethod
    def up(cls, nodes: int = 3, capacity: Optional[Dict[str, str]] = None,
           tpu_chips: int = 0, leader_elect: bool = False,
           controllers: Optional[List[str]] = None,
           full_init: bool = False) -> "Cluster":
        """kubeadm init && kubeadm join ×nodes. ``full_init=True`` runs
        the complete phase sequence (preflight → certs → control-plane
        → wait → kubeconfig → upload-config → mark-control-plane →
        addons → token → join), adding the control-plane Node and
        kube-system addons the reference installs; the default keeps
        the minimal test topology."""
        cluster = cls()
        if full_init:
            cluster.preflight_warnings = cluster.phase_preflight()
            cluster.phase_certs()
        cluster.phase_control_plane(leader_elect=leader_elect,
                                    controllers=controllers)
        if full_init:
            cluster.phase_wait_control_plane()
            cluster.phase_kubeconfig()
            cluster.phase_upload_config()
            cluster.phase_mark_control_plane()
            cluster.phase_addons()
        token = cluster.phase_bootstrap_token()
        if nodes:
            cluster.phase_join_nodes(nodes, token=token, capacity=capacity,
                                     tpu_chips=tpu_chips)
        cluster._up = True
        return cluster

    def client(self, token: Optional[str] = None) -> RestClient:
        """Porcelain client. Default = the admin credential (kubeadm's
        admin.conf is cluster-admin); pass token="" explicitly for an
        anonymous client or a component token for that identity."""
        if token is None:
            token = self.component_tokens.get("admin", "")
        return RestClient(self.apiserver.url, token=token)

    @property
    def url(self) -> str:
        return self.apiserver.url

    def down(self) -> None:
        """kubeadm reset."""
        stop = getattr(self, "_cp_heartbeat_stop", None)
        if stop is not None:
            stop.set()
        if self.nodes is not None:
            self.nodes.stop()
        if self.scheduler is not None:
            self.scheduler.stop()
        if self.controller_manager is not None:
            self.controller_manager.stop()
        if self.apiserver is not None:
            self.apiserver.shutdown_server()
        self._up = False
