"""Cluster bootstrap — the kubeadm equivalent.

Behavioral equivalent of the reference's kubeadm (``cmd/kubeadm``): phased
bring-up of a working control plane — ``init`` starts the API server,
controller manager, and scheduler (with optional leader election), mints a
bootstrap token, and ``join`` attaches nodes (here: hollow kubelets) using
that token; ``reset`` tears everything down. The phases mirror kubeadm's
(``cmd/kubeadm/app/cmd/phases``): control-plane, token, node-join.

This is also the one-call test/demo entry: ``Cluster.up(nodes=5)`` gives a
full live cluster in-process.
"""

from __future__ import annotations

import secrets
import threading
from typing import Dict, List, Optional

from kubernetes_tpu.apiserver.rest import APIServer, RestClient
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.kubemark import HollowCluster, HollowNode
from kubernetes_tpu.scheduler.scheduler import Scheduler


class Cluster:
    """A whole cluster in one object: apiserver + kcm + scheduler +
    joined nodes."""

    def __init__(self):
        self.store: Optional[ClusterStore] = None
        self.apiserver: Optional[APIServer] = None
        self.controller_manager: Optional[ControllerManager] = None
        self.scheduler: Optional[Scheduler] = None
        self.nodes = None  # HollowCluster
        self.bootstrap_token: str = ""
        self.component_tokens: Dict[str, str] = {}
        # node name -> "cert:<fingerprint>" bearer credential minted by
        # the TLS bootstrap (kubeadm's kubelet.conf client cert analog)
        self.node_credentials: Dict[str, str] = {}
        self._up = False

    # -- phases (kubeadm init) -----------------------------------------
    def phase_control_plane(self, leader_elect: bool = False,
                            controllers: Optional[List[str]] = None,
                            rbac: bool = True) -> None:
        self.store = ClusterStore()
        authorizer = None
        if rbac:
            # default-on RBAC (reference kubeadm enables the RBAC
            # authorization mode by default): bootstrap roles/bindings
            # for the control-plane components + per-component tokens
            from kubernetes_tpu.apiserver.rbac import (
                provision_bootstrap_policy,
            )

            authorizer = provision_bootstrap_policy(self.store)
        self.apiserver = APIServer(
            store=self.store,
            **({"authorizer": authorizer} if authorizer else {}),
        ).start()
        if rbac:
            for component in ("kube-scheduler", "kube-controller-manager"):
                token = secrets.token_hex(12)
                self.apiserver.tokens[token] = f"system:{component}"
                self.component_tokens[component] = token
            admin_token = secrets.token_hex(12)
            self.apiserver.tokens[admin_token] = "admin"
            self.component_tokens["admin"] = admin_token
        self.controller_manager = ControllerManager(
            self.store, controllers=controllers, leader_elect=leader_elect
        )
        self.controller_manager.start()
        self.scheduler = Scheduler.create(self.store)
        if leader_elect:
            # leader_elect covers the scheduler too, not just the
            # controller manager (reference server.go:199-208)
            self.scheduler.run_with_leader_election()
        else:
            self.scheduler.run()

    def phase_bootstrap_token(self) -> str:
        """Mint a join token, registered with the apiserver's authn
        (kubeadm token create)."""
        token = f"{secrets.token_hex(3)}.{secrets.token_hex(8)}"
        self.apiserver.tokens[token] = "system:bootstrap:node"
        self.bootstrap_token = token
        return token

    def phase_join_nodes(self, count: int, token: str = "",
                         capacity: Optional[Dict[str, str]] = None,
                         tpu_chips: int = 0) -> List[HollowNode]:
        """kubeadm join: nodes authenticate with the bootstrap token,
        complete the TLS bootstrap (CSR → auto-approve → signed client
        cert → node identity credential), register, and heartbeat.
        Per-node credentials land in ``self.node_credentials`` as
        ``cert:<fingerprint>`` bearer tokens that authenticate as
        ``system:node:<name>`` (kubeadm's kubelet.conf analog)."""
        if token and token != self.bootstrap_token:
            raise PermissionError("invalid bootstrap token")
        if self.nodes is None:
            nlc = self.controller_manager.controllers.get("nodelifecycle")
            self.nodes = HollowCluster(
                self.store,
                heartbeat_fn=nlc.heartbeat if nlc is not None else None,
            )
        started = self.nodes.start_nodes(count, capacity=capacity,
                                         tpu_chips=tpu_chips)
        if token:
            for node in started:
                try:
                    self.node_credentials[node.name] = \
                        self.tls_bootstrap(node.name, token)
                except Exception:  # noqa: BLE001 — joining stays usable
                    # even when the CSR trio isn't running (subset
                    # controller configs); the credential is then absent
                    pass
        return started

    def tls_bootstrap(self, node_name: str, token: str,
                      timeout: float = 15.0) -> str:
        """The kubeadm TLS bootstrap, through the API: the bootstrap
        token submits a client CSR (subject CN=system:node:<name>), the
        csrapproving controller auto-approves it (bootstrap identity +
        kubelet client signer), csrsigning issues the certificate, and
        the certificate's fingerprint becomes the node's API credential
        (x509 authn stand-in — rest.py resolve_cert_fingerprint)."""
        import hashlib
        import time as _time

        from kubernetes_tpu.api.types import CertificateSigningRequest
        from kubernetes_tpu.controllers.certificates import (
            KUBE_APISERVER_CLIENT_KUBELET_SIGNER,
        )

        client = self.client(token)
        csr = CertificateSigningRequest(
            request=f"CN=system:node:{node_name},O=system:nodes",
            signer_name=KUBE_APISERVER_CLIENT_KUBELET_SIGNER,
            usages=["client auth"],
        )
        csr.metadata.name = f"node-csr-{node_name}"
        try:
            client.create(csr)
        except ValueError:
            pass   # rejoin: the CSR exists; wait for its certificate
        deadline = _time.time() + timeout
        while _time.time() < deadline:
            live = client.get("CertificateSigningRequest",
                              csr.metadata.name, namespace=None)
            if live is not None and live.certificate:
                fp = hashlib.sha256(live.certificate.encode()).hexdigest()
                return f"cert:{fp}"
            _time.sleep(0.05)
        raise TimeoutError(
            f"TLS bootstrap for {node_name}: CSR not signed in time")

    # -- porcelain ------------------------------------------------------
    @classmethod
    def up(cls, nodes: int = 3, capacity: Optional[Dict[str, str]] = None,
           tpu_chips: int = 0, leader_elect: bool = False,
           controllers: Optional[List[str]] = None) -> "Cluster":
        """kubeadm init && kubeadm join ×nodes."""
        cluster = cls()
        cluster.phase_control_plane(leader_elect=leader_elect,
                                    controllers=controllers)
        token = cluster.phase_bootstrap_token()
        if nodes:
            cluster.phase_join_nodes(nodes, token=token, capacity=capacity,
                                     tpu_chips=tpu_chips)
        cluster._up = True
        return cluster

    def client(self, token: Optional[str] = None) -> RestClient:
        """Porcelain client. Default = the admin credential (kubeadm's
        admin.conf is cluster-admin); pass token="" explicitly for an
        anonymous client or a component token for that identity."""
        if token is None:
            token = self.component_tokens.get("admin", "")
        return RestClient(self.apiserver.url, token=token)

    @property
    def url(self) -> str:
        return self.apiserver.url

    def down(self) -> None:
        """kubeadm reset."""
        if self.nodes is not None:
            self.nodes.stop()
        if self.scheduler is not None:
            self.scheduler.stop()
        if self.controller_manager is not None:
            self.controller_manager.stop()
        if self.apiserver is not None:
            self.apiserver.shutdown_server()
        self._up = False
