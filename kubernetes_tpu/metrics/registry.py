"""Prometheus-style metrics primitives (the ``component-base/metrics`` +
``legacyregistry`` equivalent): counters, gauges, histograms with label
vectors, and text exposition in the Prometheus format for the /metrics
endpoint."""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple

LabelValues = Tuple[str, ...]


class _Metric:
    def __init__(self, name: str, help_text: str, label_names: Sequence[str] = ()):
        self.name = name
        self.help = help_text
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()

    def with_labels(self, *values: str):
        raise NotImplementedError


class Counter(_Metric):
    TYPE = "counter"

    def __init__(self, name, help_text, label_names=()):
        super().__init__(name, help_text, label_names)
        self._values: Dict[LabelValues, float] = {}

    def inc(self, *label_values: str, amount: float = 1.0) -> None:
        with self._lock:
            key = tuple(label_values)
            self._values[key] = self._values.get(key, 0.0) + amount

    def get(self, *label_values: str) -> float:
        with self._lock:
            return self._values.get(tuple(label_values), 0.0)

    def collect(self):
        with self._lock:
            return [(self.name, k, v) for k, v in self._values.items()]


class Gauge(_Metric):
    TYPE = "gauge"

    def __init__(self, name, help_text, label_names=()):
        super().__init__(name, help_text, label_names)
        self._values: Dict[LabelValues, float] = {}

    def set(self, value: float, *label_values: str) -> None:
        with self._lock:
            self._values[tuple(label_values)] = value

    def inc(self, *label_values: str, amount: float = 1.0) -> None:
        with self._lock:
            key = tuple(label_values)
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, *label_values: str) -> None:
        self.inc(*label_values, amount=-1.0)

    def get(self, *label_values: str) -> float:
        with self._lock:
            return self._values.get(tuple(label_values), 0.0)

    def collect(self):
        with self._lock:
            return [(self.name, k, v) for k, v in self._values.items()]


DEFAULT_BUCKETS = (
    0.001, 0.002, 0.005, 0.01, 0.02, 0.05, 0.1, 0.2, 0.5, 1.0, 2.0, 5.0,
    10.0, 20.0, 50.0,
)


class Histogram(_Metric):
    TYPE = "histogram"

    def __init__(self, name, help_text, label_names=(), buckets=DEFAULT_BUCKETS):
        super().__init__(name, help_text, label_names)
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[LabelValues, List[int]] = {}
        self._sums: Dict[LabelValues, float] = {}
        self._totals: Dict[LabelValues, int] = {}

    def observe(self, value: float, *label_values: str) -> None:
        with self._lock:
            key = tuple(label_values)
            counts = self._counts.get(key)
            if counts is None:
                counts = [0] * (len(self.buckets) + 1)
                self._counts[key] = counts
            counts[bisect.bisect_left(self.buckets, value)] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def count(self, *label_values: str) -> int:
        with self._lock:
            return self._totals.get(tuple(label_values), 0)

    def sum(self, *label_values: str) -> float:
        with self._lock:
            return self._sums.get(tuple(label_values), 0.0)

    def quantile(self, q: float, *label_values: str) -> float:
        """Bucket-interpolated quantile (what the perf harness scrapes)."""
        with self._lock:
            key = tuple(label_values)
            counts = self._counts.get(key)
            total = self._totals.get(key, 0)
        if not counts or total == 0:
            return 0.0
        target = q * total
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= target:
                return self.buckets[i] if i < len(self.buckets) else self.buckets[-1]
        return self.buckets[-1]

    def collect(self):
        with self._lock:
            return [
                (self.name, k, self._sums.get(k, 0.0), self._totals.get(k, 0))
                for k in self._counts
            ]


class MetricsRegistry:
    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            self._metrics[metric.name] = metric
            return metric

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def expose(self) -> str:
        """Prometheus text exposition."""
        lines = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.TYPE}")
            if isinstance(m, Histogram):
                for name, labels, total_sum, total in m.collect():
                    label_str = _fmt_labels(m.label_names, labels)
                    lines.append(f"{name}_sum{label_str} {total_sum}")
                    lines.append(f"{name}_count{label_str} {total}")
            else:
                for name, labels, value in m.collect():
                    lines.append(f"{name}{_fmt_labels(m.label_names, labels)} {value}")
        return "\n".join(lines) + "\n"


def _fmt_labels(names, values) -> str:
    if not values:
        return ""
    pairs = ",".join(f'{n}="{v}"' for n, v in zip(names, values))
    return "{" + pairs + "}"
