"""Prometheus-style metrics primitives (the ``component-base/metrics`` +
``legacyregistry`` equivalent): counters, gauges, histograms with label
vectors, and text exposition in the Prometheus format for the /metrics
endpoint."""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple

LabelValues = Tuple[str, ...]


class _Metric:
    def __init__(self, name: str, help_text: str, label_names: Sequence[str] = ()):
        self.name = name
        self.help = help_text
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()

    def with_labels(self, *values: str):
        raise NotImplementedError


class Counter(_Metric):
    TYPE = "counter"

    def __init__(self, name, help_text, label_names=()):
        super().__init__(name, help_text, label_names)
        self._values: Dict[LabelValues, float] = {}

    def inc(self, *label_values: str, amount: float = 1.0) -> None:
        with self._lock:
            key = tuple(label_values)
            self._values[key] = self._values.get(key, 0.0) + amount

    def get(self, *label_values: str) -> float:
        with self._lock:
            return self._values.get(tuple(label_values), 0.0)

    def collect(self):
        with self._lock:
            return [(self.name, k, v) for k, v in self._values.items()]


class Gauge(_Metric):
    TYPE = "gauge"

    def __init__(self, name, help_text, label_names=()):
        super().__init__(name, help_text, label_names)
        self._values: Dict[LabelValues, float] = {}

    def set(self, value: float, *label_values: str) -> None:
        with self._lock:
            self._values[tuple(label_values)] = value

    def inc(self, *label_values: str, amount: float = 1.0) -> None:
        with self._lock:
            key = tuple(label_values)
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, *label_values: str) -> None:
        self.inc(*label_values, amount=-1.0)

    def get(self, *label_values: str) -> float:
        with self._lock:
            return self._values.get(tuple(label_values), 0.0)

    def collect(self):
        with self._lock:
            return [(self.name, k, v) for k, v in self._values.items()]


DEFAULT_BUCKETS = (
    0.001, 0.002, 0.005, 0.01, 0.02, 0.05, 0.1, 0.2, 0.5, 1.0, 2.0, 3.0,
    5.0, 7.5, 10.0, 15.0, 20.0, 30.0, 40.0, 50.0,
)


class Histogram(_Metric):
    TYPE = "histogram"

    def __init__(self, name, help_text, label_names=(), buckets=DEFAULT_BUCKETS):
        super().__init__(name, help_text, label_names)
        self.buckets = tuple(sorted(buckets))
        # one dict lookup per observe: series = [counts list, sum, total]
        # (observe runs ~10x per scheduled pod on the commit hot path)
        self._series: Dict[LabelValues, list] = {}

    def _get_series(self, key: LabelValues) -> list:
        series = self._series.get(key)
        if series is None:
            series = [[0] * (len(self.buckets) + 1), 0.0, 0]
            self._series[key] = series
        return series

    def observe(self, value: float, *label_values: str) -> None:
        with self._lock:
            series = self._get_series(label_values)
            series[0][bisect.bisect_left(self.buckets, value)] += 1
            series[1] += value
            series[2] += 1

    def observe_many(self, values, *label_values: str) -> None:
        """Bulk observe: one lock acquisition for a whole batch (the
        commit path observes per pod — at thousands of pods per batch
        the per-call lock round-trips add up)."""
        if not values:
            return
        with self._lock:
            series = self._get_series(label_values)
            counts = series[0]
            buckets = self.buckets
            total = 0.0
            for v in values:
                counts[bisect.bisect_left(buckets, v)] += 1
                total += v
            series[1] += total
            series[2] += len(values)

    def clear(self) -> None:
        """Drop every series — for callers that report per-interval
        numbers (the bench diag consumes its histograms between rows)."""
        with self._lock:
            self._series.clear()

    def bucket_counts(self, *label_values: str) -> List[int]:
        """Per-bucket observation counts for one series (len(buckets)+1,
        last entry = +Inf overflow). The public face of the bucket table:
        the bench ``diag:`` line's e2e_buckets text is rendered from
        THIS accessor (harness/diagfmt.py) against the same series
        /metrics exposes, so the two can never disagree."""
        with self._lock:
            series = self._series.get(tuple(label_values))
            return list(series[0]) if series else []

    def count(self, *label_values: str) -> int:
        with self._lock:
            series = self._series.get(tuple(label_values))
            return series[2] if series else 0

    def sum(self, *label_values: str) -> float:
        with self._lock:
            series = self._series.get(tuple(label_values))
            return series[1] if series else 0.0

    def quantile(self, q: float, *label_values: str) -> float:
        """Bucket-interpolated quantile (prometheus histogram_quantile
        semantics: linear interpolation WITHIN the target bucket). The
        previous upper-edge report collapsed every breach between two
        edges to the higher edge — a 26s stall read as exactly "50s"
        with no shape information (VERDICT r4 weak #5)."""
        with self._lock:
            series = self._series.get(tuple(label_values))
            counts = list(series[0]) if series else None
        if not counts:
            return 0.0
        return quantile_from_counts(counts, self.buckets, q)

    def collect(self):
        with self._lock:
            return [
                (self.name, k, series[1], series[2])
                for k, series in self._series.items()
            ]

    def collect_full(self):
        """Per-series (labels, bucket_counts, sum, count) — the bucket
        table the text exposition (and so the federation parser) reads.
        ``collect`` keeps its historical sum/count-only shape for the
        diag consumers."""
        with self._lock:
            return [
                (k, list(series[0]), series[1], series[2])
                for k, series in self._series.items()
            ]


def quantile_from_counts(counts: Sequence[int], edges: Sequence[float],
                         q: float) -> float:
    """Bucket-interpolated quantile over a raw count vector (the
    ``Histogram.quantile`` math, reusable for aggregated or windowed
    delta vectors — the SLO engine and the freshness row summary both
    quantile counts that no single live series holds)."""
    total = sum(counts)
    if not counts or total == 0:
        return 0.0
    target = q * total
    cum = 0
    for i, c in enumerate(counts):
        prev_cum = cum
        cum += c
        if cum >= target:
            if i >= len(edges):
                return edges[-1] if edges else 0.0
            lo = edges[i - 1] if i > 0 else 0.0
            hi = edges[i]
            if c == 0:
                return hi
            return lo + (hi - lo) * (target - prev_cum) / c
    return edges[-1] if edges else 0.0


class MetricsRegistry:
    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            self._metrics[metric.name] = metric
            return metric

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def all_metrics(self) -> List[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    def expose(self) -> str:
        """Prometheus text exposition. Histograms render the FULL
        standard shape — cumulative ``_bucket{le="..."}`` lines
        (``+Inf`` included) plus ``_sum``/``_count`` — so a remote
        scraper (metrics/federation.py) can reconstruct the series
        exactly; parse(expose(x)) ≡ x is CI-enforced by the metrics
        lint."""
        lines = []
        for m in self.all_metrics():
            lines.append(f"# HELP {m.name} {_esc_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.TYPE}")
            if isinstance(m, Histogram):
                edges = [_fmt_float(b) for b in m.buckets] + ["+Inf"]
                for labels, counts, total_sum, total in m.collect_full():
                    cum = 0
                    for edge, c in zip(edges, counts):
                        cum += c
                        label_str = _fmt_labels(
                            m.label_names + ("le",), labels + (edge,))
                        lines.append(f"{m.name}_bucket{label_str} {cum}")
                    label_str = _fmt_labels(m.label_names, labels)
                    lines.append(f"{m.name}_sum{label_str} {total_sum}")
                    lines.append(f"{m.name}_count{label_str} {total}")
            else:
                for name, labels, value in m.collect():
                    lines.append(
                        f"{name}{_fmt_labels(m.label_names, labels)} {value}")
        return "\n".join(lines) + "\n"


def _fmt_float(v: float) -> str:
    """Bucket-edge rendering: integral edges drop the trailing .0 the
    way Prometheus clients do (le="1" not le="1.0")."""
    return str(int(v)) if float(v) == int(v) else repr(float(v))


def _esc_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _esc_label(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(names, values) -> str:
    if not values:
        return ""
    pairs = ",".join(
        f'{n}="{_esc_label(v)}"' for n, v in zip(names, values))
    return "{" + pairs + "}"
