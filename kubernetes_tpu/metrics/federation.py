"""Multi-process metrics federation: scrape, parse, merge.

The bench/chaos harnesses spawn real child processes (apiservers,
creators, aggressor tenants) and each child keeps its own metrics
registry — until this module, the only cross-process metrics path was
the APF-specific ``/debug/apf`` JSON side channel mirrored by
``apf_metrics().absorb_snapshot``, one hand-written mapping per metric
family. This module is the generic path (the Prometheus federation
pattern):

- ``parse_exposition`` parses the Prometheus text format our own
  ``MetricsRegistry.expose`` renders (counters, gauges, full histograms
  with ``_bucket{le=...}`` lines) into structured families —
  ``parse(expose(x)) ≡ x`` is CI-enforced by the metrics-lint test, so
  exposition drift can never silently break scraping;
- ``MetricsFederation`` pulls ``/metrics`` from every component and
  merges the families into ONE registry with an ``instance`` label
  prepended (last scrape wins per instance, Prometheus sample
  semantics — repeated scrapes never double-count);
- ``fold=True`` additionally folds a remote instance's COUNTER families
  into this process's same-name counters by cumulative delta (with
  counter-reset detection for restarted children), which is what lets
  ``bench.py``'s diag segments keep reading their usual local series
  for remote-server rows without one absorb function per family.

The merged view lives in the federation's own registry rather than the
process default registry: both processes run the same code, so every
child family name collides with a live local metric of a DIFFERENT
label shape — ``federation_registry().expose()`` is the cluster-wide
exposition, the default registry stays this process's own truth.
"""

from __future__ import annotations

import re
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.metrics.registry import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)

METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$")
_LABEL_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


class ExpositionError(ValueError):
    """Malformed Prometheus text exposition."""


def _unescape(value: str) -> str:
    """Single left-to-right pass (sequential str.replace would decode
    an escaped backslash followed by 'n' — ``\\\\n`` on the wire, a
    literal backslash then the letter — as a newline)."""
    if "\\" not in value:
        return value
    out = []
    i = 0
    n = len(value)
    while i < n:
        c = value[i]
        if c == "\\" and i + 1 < n:
            nxt = value[i + 1]
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if nxt in ('"', "\\"):
                out.append(nxt)
                i += 2
                continue
        out.append(c)
        i += 1
    return "".join(out)


@dataclass
class HistSeries:
    """One histogram series reconstructed from its exposition lines:
    per-bucket (upper edge, NON-cumulative count) pairs ordered by
    edge with the ``+Inf`` overflow last, plus sum/count."""

    bucket_edges: Tuple[float, ...] = ()     # finite edges only
    bucket_counts: List[int] = field(default_factory=list)
    sum: float = 0.0
    count: int = 0


@dataclass
class Family:
    name: str
    type: str = "untyped"
    help: str = ""
    label_names: Tuple[str, ...] = ()
    # counter/gauge: labels tuple -> value
    samples: Dict[Tuple[str, ...], float] = field(default_factory=dict)
    # histogram: labels tuple (without "le") -> HistSeries
    histograms: Dict[Tuple[str, ...], HistSeries] = field(
        default_factory=dict)


def _parse_labels(body: Optional[str]) -> Dict[str, str]:
    if not body:
        return {}
    out: Dict[str, str] = {}
    for m in _LABEL_PAIR_RE.finditer(body):
        out[m.group(1)] = _unescape(m.group(2))
    # commas between pairs + optional trailing comma are the only
    # other characters allowed; anything else is a torn label set
    rest = _LABEL_PAIR_RE.sub("", body).replace(",", "").strip()
    if rest:
        raise ExpositionError(f"malformed label set {{{body}}}")
    return out


def parse_exposition(text: str) -> Dict[str, Family]:
    """Prometheus text exposition → name → Family. Histogram families
    fold their ``_bucket``/``_sum``/``_count`` samples back into
    per-series bucket tables (de-cumulated). Raises ExpositionError on
    lines that are neither comments, blank, nor valid samples."""
    families: Dict[str, Family] = {}
    # histogram suffix routing: base name -> family (populated when a
    # TYPE histogram line is seen)
    hist_bases: Dict[str, Family] = {}

    def family(name: str) -> Family:
        fam = families.get(name)
        if fam is None:
            fam = families[name] = Family(name)
        return fam

    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):]
            name, _, help_text = rest.partition(" ")
            family(name).help = _unescape(help_text)
            continue
        if line.startswith("# TYPE "):
            rest = line[len("# TYPE "):]
            name, _, mtype = rest.partition(" ")
            fam = family(name)
            fam.type = mtype.strip()
            if fam.type == "histogram":
                hist_bases[name] = fam
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ExpositionError(f"malformed sample line: {line!r}")
        name, label_body, value_s = m.group(1), m.group(2), m.group(3)
        labels = _parse_labels(label_body)
        try:
            value = float(value_s)
        except ValueError:
            raise ExpositionError(f"malformed value in: {line!r}")
        base = None
        suffix = None
        for sfx in ("_bucket", "_sum", "_count"):
            if name.endswith(sfx) and name[: -len(sfx)] in hist_bases:
                base, suffix = name[: -len(sfx)], sfx
                break
        if base is not None:
            fam = hist_bases[base]
            le = labels.pop("le", None)
            key_names = tuple(labels.keys())
            if not fam.label_names and key_names:
                fam.label_names = key_names
            key = tuple(labels[n] for n in fam.label_names) \
                if fam.label_names else ()
            series = fam.histograms.get(key)
            if series is None:
                series = fam.histograms[key] = HistSeries()
            if suffix == "_bucket":
                if le is None:
                    raise ExpositionError(
                        f"histogram bucket without le: {line!r}")
                edge = float("inf") if le == "+Inf" else float(le)
                # cumulative on the wire → de-cumulate against the
                # running total (edges arrive in ascending order)
                prev_cum = sum(series.bucket_counts)
                series.bucket_counts.append(int(value) - prev_cum)
                if edge != float("inf"):
                    series.bucket_edges = series.bucket_edges + (edge,)
            elif suffix == "_sum":
                series.sum = value
            else:
                series.count = int(value)
            continue
        fam = family(name)
        key_names = tuple(labels.keys())
        if not fam.label_names and key_names:
            fam.label_names = key_names
        key = tuple(labels.get(n, "") for n in fam.label_names) \
            if fam.label_names else ()
        fam.samples[key] = value
    return families


def families_from_registry(reg: MetricsRegistry) -> Dict[str, Family]:
    """The same Family structures built directly from the registry's
    live objects — the lint's ground truth for parse(expose(x)) ≡ x."""
    out: Dict[str, Family] = {}
    for m in reg.all_metrics():
        fam = Family(m.name, m.TYPE, m.help, tuple(m.label_names))
        if isinstance(m, Histogram):
            for labels, counts, total_sum, total in m.collect_full():
                fam.histograms[tuple(labels)] = HistSeries(
                    bucket_edges=tuple(float(b) for b in m.buckets),
                    bucket_counts=list(counts),
                    sum=total_sum, count=total)
        else:
            for _name, labels, value in m.collect():
                fam.samples[tuple(labels)] = float(value)
        out[m.name] = fam
    return out


def lint_family(fam: Family) -> List[str]:
    """Prometheus-validity problems with one family (metrics-lint)."""
    problems: List[str] = []
    if not METRIC_NAME_RE.match(fam.name):
        problems.append(f"invalid metric name {fam.name!r}")
    if fam.type not in ("counter", "gauge", "histogram", "untyped"):
        problems.append(f"{fam.name}: unknown type {fam.type!r}")
    for ln in fam.label_names:
        if not LABEL_NAME_RE.match(ln):
            problems.append(f"{fam.name}: invalid label name {ln!r}")
        if ln.startswith("__"):
            problems.append(f"{fam.name}: reserved label name {ln!r}")
    if fam.type == "histogram" and "le" in fam.label_names:
        problems.append(f"{fam.name}: histogram declares 'le' label")
    return problems


class MetricsFederation:
    """Pulls component expositions and maintains the merged,
    instance-labelled cluster view (see module docstring)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 fold_registry: Optional[MetricsRegistry] = None):
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self._fold_registry = fold_registry
        self._lock = threading.Lock()
        # (name, labels-sans-instance, instance) -> last folded
        # cumulative value (counter-reset detection baseline)
        self._folded: Dict[tuple, float] = {}
        self.scrape_errors: List[str] = []

    # -- ingestion -----------------------------------------------------
    def absorb_text(self, text: str, instance: str,
                    fold: bool = False) -> int:
        """Merge one component's exposition under ``instance``. Returns
        the number of families absorbed. Last scrape wins per instance;
        with ``fold``, counter families are ALSO folded (by cumulative
        delta) into this process's same-name counters."""
        families = parse_exposition(text)
        for fam in families.values():
            self._upsert(fam, instance)
            if fold:
                self._fold(fam, instance)
        return len(families)

    def absorb_registry(self, reg: MetricsRegistry, instance: str) -> int:
        """Mirror a LOCAL registry into the federation (the parent
        process is a component too). Rides the same render→parse path a
        remote scrape takes, so the merged view never depends on which
        side of a process boundary a component runs."""
        return self.absorb_text(reg.expose(), instance)

    def scrape(self, url: str, instance: str, token: str = "",
               timeout: float = 5.0, fold: bool = False) -> bool:
        """HTTP GET a component's ``/metrics`` and absorb it. ``url``
        is the server base (``http://host:port``) or the full /metrics
        URL. Best-effort by contract (a dying child must not fail the
        bench row): failures land in ``scrape_errors`` and return
        False."""
        import http.client

        if not url.endswith("/metrics"):
            url = url.rstrip("/") + "/metrics"
        rest = url.split("://", 1)[-1]
        hostport, _, path = rest.partition("/")
        host, _, port = hostport.partition(":")
        try:
            conn = http.client.HTTPConnection(
                host, int(port or 80), timeout=timeout)
            try:
                headers = {"Authorization": f"Bearer {token}"} \
                    if token else {}
                conn.request("GET", "/" + path, headers=headers)
                resp = conn.getresponse()
                body = resp.read()
                if resp.status != 200:
                    raise ExpositionError(
                        f"HTTP {resp.status} from {url}")
                self.absorb_text(body.decode(), instance, fold=fold)
                return True
            finally:
                conn.close()
        except Exception as e:  # noqa: BLE001 — scraping is best-effort
            self.scrape_errors.append(f"{instance} {url}: {e}")
            return False

    # -- merge ---------------------------------------------------------
    def _upsert(self, fam: Family, instance: str) -> None:
        label_names = ("instance",) + tuple(fam.label_names)
        with self._lock:
            metric = self.registry.get(fam.name)
            if fam.type == "histogram":
                edges = None
                for series in fam.histograms.values():
                    edges = series.bucket_edges
                    break
                if edges is None and not isinstance(metric, Histogram):
                    return      # empty family, nothing to merge yet
                if (not isinstance(metric, Histogram)
                        or metric.label_names != label_names
                        or (edges is not None
                            and tuple(metric.buckets) != tuple(edges))):
                    metric = self.registry.register(Histogram(
                        fam.name, fam.help, label_names,
                        buckets=edges or DEFAULT_BUCKETS))
                self._drop_instance_series(metric, instance)
                for labels, series in fam.histograms.items():
                    counts = list(series.bucket_counts)
                    want = len(metric.buckets) + 1
                    counts += [0] * (want - len(counts))
                    with metric._lock:
                        metric._series[(instance,) + labels] = [
                            counts[:want], series.sum, series.count]
                return
            cls = Counter if fam.type == "counter" else Gauge
            if (not isinstance(metric, (Counter, Gauge))
                    or metric.TYPE != cls.TYPE
                    or metric.label_names != label_names):
                metric = self.registry.register(
                    cls(fam.name, fam.help, label_names))
            self._drop_instance_series(metric, instance)
            with metric._lock:
                for labels, value in fam.samples.items():
                    # sample semantics: SET the mirrored series (a
                    # counter mirror is still monotonic per instance
                    # because the source is)
                    metric._values[(instance,) + labels] = value

    @staticmethod
    def _drop_instance_series(metric, instance: str) -> None:
        table = metric._series if isinstance(metric, Histogram) \
            else metric._values
        with metric._lock:
            for key in [k for k in table if k and k[0] == instance]:
                del table[key]

    def _fold(self, fam: Family, instance: str,
              into: Optional[MetricsRegistry] = None) -> None:
        """Fold a remote counter family into the local same-name
        counter by cumulative delta — the generic replacement for the
        per-family ``absorb_snapshot`` mappings. Counter resets (a
        fresh child under a reused instance name) restart the baseline
        so the new child's full total folds in."""
        into = into if into is not None else self._fold_registry
        if fam.type != "counter" or into is None:
            return
        target = into.get(fam.name)
        if not isinstance(target, Counter) \
                or target.label_names != tuple(fam.label_names):
            return
        for labels, value in fam.samples.items():
            key = (fam.name, labels, instance)
            with self._lock:
                prev = self._folded.get(key, 0.0)
                if value < prev:
                    prev = 0.0          # child restarted: counter reset
                self._folded[key] = value
            if value > prev:
                target.inc(*labels, amount=value - prev)

    def fold_samples(self, name: str, label_names: Tuple[str, ...],
                     samples: Dict[Tuple[str, ...], float],
                     instance: str,
                     into: Optional[MetricsRegistry] = None) -> None:
        """Fold one counter family given as plain samples — the compat
        entry point ``apf_metrics.absorb_snapshot`` wraps, so the
        legacy /debug/apf JSON path and the scrape path share ONE delta
        ledger. ``into`` overrides the fold-target registry."""
        fam = Family(name, "counter", "", tuple(label_names),
                     samples=dict(samples))
        self._fold(fam, instance, into=into)

    # -- queries -------------------------------------------------------
    def instances(self, name: Optional[str] = None) -> set:
        """Distinct ``instance`` label values merged so far (for one
        family, or across all) — the cardinality the federation
        acceptance asserts on."""
        out = set()
        for m in self.registry.all_metrics():
            if name is not None and m.name != name:
                continue
            table = m._series if isinstance(m, Histogram) else m._values
            with m._lock:
                out.update(k[0] for k in table if k)
        return out

    def counter_total(self, name: str) -> float:
        """Sum of one counter family across every instance + labels."""
        m = self.registry.get(name)
        if not isinstance(m, (Counter, Gauge)):
            return 0.0
        return sum(v for _n, _k, v in m.collect())

    def series(self, name: str):
        """The merged metric object (instance label first), or None."""
        return self.registry.get(name)

    def drop_instance(self, instance: str) -> None:
        """Forget one instance's merged series (fold baselines are
        kept: a re-scrape of the same still-running child must not
        double-fold)."""
        for m in self.registry.all_metrics():
            self._drop_instance_series(m, instance)

    def forget_instance(self, instance: str) -> None:
        """Forget one instance's merged series AND its fold baselines —
        for callers that reuse an instance name across child-process
        generations (the bench harness spawns a fresh apiserver per
        row): the next child's totals must fold in full, not as a
        delta against a dead process's counters."""
        self.drop_instance(instance)
        with self._lock:
            for key in [k for k in self._folded if k[2] == instance]:
                del self._folded[key]

    def clear(self) -> None:
        with self._lock:
            self._folded.clear()
            self.scrape_errors = []
        for m in self.registry.all_metrics():
            table = m._series if isinstance(m, Histogram) else m._values
            with m._lock:
                table.clear()


_default: Optional[MetricsFederation] = None
_default_lock = threading.Lock()


def metrics_federation() -> MetricsFederation:
    """Process-wide federation (the legacyregistry pattern): merged
    view in its own registry, counter folds target the process default
    registry."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                from kubernetes_tpu.metrics import default_registry

                _default = MetricsFederation(
                    fold_registry=default_registry())
    return _default
