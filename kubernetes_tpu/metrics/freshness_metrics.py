"""Cluster SLI metrics: watch/informer freshness.

The fabric that feeds the scheduler was blind about its own staleness:
watch events ride a coalescing flush window plus queues with zero
latency accounting, and nothing measured how old the snapshot a solve
cycle runs against actually is. These series close that gap — they are
the SLIs the SLO engine (``observability/slo.py``) evaluates live:

- ``watch_delivery_seconds{kind}`` — store-commit → client decode,
  end-to-end across the wire: includes the server's coalescing flush
  window, the frame queue, chunked-transfer delivery, and the client's
  batch decode. Events are stamped ONCE at store dispatch time
  (``Event.ts``) and the stamp rides the cached per-event encoding, so
  N watchers measure real per-watcher delivery without re-stamping.
- ``informer_lag_seconds{kind}`` — store-commit → informer handler
  dispatch for ``SharedInformerFactory`` consumers (the controllers'
  ingestion path): delivery PLUS the informer's delta-FIFO backlog.
- ``informer_queue_depth`` — the factory FIFO's drain-time backlog
  (how many events one dispatch wakeup had to absorb).
- ``snapshot_staleness_seconds`` — per solve cycle, the age of the
  newest event reflected in the planes the solver encoded (recorded
  into the devprof cycle record and the tracer, so staleness is
  attributable per cycle and so per pod).

Hot-path budget matches the tracer/devprof bar: stamping is one
``time.time()`` per DISPATCH BATCH, observation is one
``observe_many`` per decoded batch — measured by the interleaved
on/off A/B (``bench.py --config freshab``). ``KTPU_FRESHNESS=off``
(or ``configure(enabled=False)``) disables BOTH the store-commit
stamping and the observation, so the A/B's off arm sheds the whole
layer.
"""

from __future__ import annotations

import os
from typing import Optional

from kubernetes_tpu.metrics.fabric_metrics import _gauge, _histogram
from kubernetes_tpu.metrics.registry import MetricsRegistry

# watch delivery / informer lag are short-fuse series: the buckets
# resolve the 2ms flush window at the bottom and a stalled watch at the
# top (a 10s+ delivery is an outage, not a latency)
_DELIVERY_BUCKETS = (0.001, 0.002, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1.0, 2.5, 5.0, 10.0)
_STALENESS_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                      2.0, 5.0, 10.0, 30.0)


class FreshnessMetrics:
    def __init__(self, registry: Optional[MetricsRegistry] = None):
        if registry is None:
            from kubernetes_tpu.metrics import default_registry

            registry = default_registry()
        self.registry = registry
        self.enabled = os.environ.get("KTPU_FRESHNESS", "") != "off"
        self.watch_delivery_seconds = _histogram(
            registry, "watch_delivery_seconds",
            "Watch-event propagation latency, store commit to client "
            "decode (includes the server's coalescing flush window and "
            "the frame queue), by kind",
            ("kind",), buckets=_DELIVERY_BUCKETS,
        )
        self.informer_lag_seconds = _histogram(
            registry, "informer_lag_seconds",
            "Store commit to informer handler dispatch, by kind "
            "(delivery plus the shared informer factory's delta-FIFO "
            "backlog)",
            ("kind",), buckets=_DELIVERY_BUCKETS,
        )
        self.informer_queue_depth = _gauge(
            registry, "informer_queue_depth",
            "Events drained from the shared informer factory's delta "
            "FIFO by the last dispatch wakeup (backlog per wakeup)",
        )
        self.snapshot_staleness_seconds = _histogram(
            registry, "snapshot_staleness_seconds",
            "Per solve cycle: age of the newest watch event reflected "
            "in the encoded planes the solver ran against",
            buckets=_STALENESS_BUCKETS,
        )
        self.replication_lag_seconds = _histogram(
            registry, "replication_lag_seconds",
            "Read-tier replication lag, owner commit to replica apply, "
            "by replica id (the staleness the fence state machine "
            "evaluates against the lag budget)",
            ("replica",), buckets=_STALENESS_BUCKETS,
        )

    def configure(self, enabled: Optional[bool] = None) -> None:
        if enabled is not None:
            self.enabled = enabled

    def reset_window(self) -> None:
        """Fresh per-row window (mirrors the tracer's per-row clear and
        the apf queue-wait clear): each bench row's ``freshness``
        sub-object must describe THAT row, not the process lifetime."""
        self.watch_delivery_seconds.clear()
        self.informer_lag_seconds.clear()
        self.snapshot_staleness_seconds.clear()
        self.replication_lag_seconds.clear()


_default: Optional[FreshnessMetrics] = None


def freshness_metrics() -> FreshnessMetrics:
    """Process-wide FreshnessMetrics bound to the default registry
    (the legacyregistry pattern the other metric modules follow)."""
    global _default
    if _default is None:
        _default = FreshnessMetrics()
    return _default


def freshness_row_summary(devprof_summary: Optional[dict] = None,
                          slo_statuses: Optional[dict] = None) -> dict:
    """The ``freshness`` sub-object every bench row carries: watch
    delivery p99, max snapshot staleness, and the SLO verdicts — the
    SLI layer's numbers in the driver-committed artifact."""
    from kubernetes_tpu.metrics.registry import quantile_from_counts

    fm = freshness_metrics()
    out: dict = {}
    wd = fm.watch_delivery_seconds
    per_kind = {}
    events = 0
    # overall p99 interpolates over the bucket counts SUMMED across
    # kinds — the max of per-kind p99s would let one slow event in a
    # 4-event kind misreport a row that delivered 30k fast Pod events
    agg = [0] * (len(wd.buckets) + 1)
    for labels, counts, _sum, count in wd.collect_full():
        if not count:
            continue
        kind = labels[0] if labels else ""
        per_kind[kind] = round(
            quantile_from_counts(counts, wd.buckets, 0.99) * 1000, 2)
        for i, c in enumerate(counts):
            agg[i] += c
        events += count
    if events:
        out["watch_delivery_p99_ms"] = round(
            quantile_from_counts(agg, wd.buckets, 0.99) * 1000, 2)
        out["watch_delivery_events"] = events
        out["watch_delivery_p99_ms_by_kind"] = per_kind
    ss = fm.snapshot_staleness_seconds
    if ss.count():
        out["snapshot_staleness_p99_ms"] = round(
            ss.quantile(0.99) * 1000, 2)
    if devprof_summary and devprof_summary.get("max_staleness_s") \
            is not None:
        out["max_snapshot_staleness_ms"] = round(
            devprof_summary["max_staleness_s"] * 1000, 2)
    if slo_statuses:
        out["slo"] = {
            name: ("violated" if s.get("violated") else "ok")
            for name, s in sorted(slo_statuses.items())
            if s.get("events_fast") or s.get("violated")
        }
    return out
