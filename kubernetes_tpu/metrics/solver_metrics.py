"""Solver/device profiling metrics (the /metrics face of
``observability/devprof.py``): per-bucket XLA compile counts and wall
time, the dispatch-vs-block split around the solver call, pad occupancy,
and host↔device transfer volume.

The reference exposes nothing like this (its scheduler has no device),
but the posture mirrors ``scheduler_perf``'s per-op metrics collection:
every quantity a perf claim rests on must be scrapeable from the live
process, not re-derived by a fresh profiling run. Cycle ids recorded by
devprof correlate these series with the flight-recorder tracer's
``solve.*`` spans, so a slow cycle found in ``/debug/trace`` links to
its compile/wait breakdown here.
"""

from __future__ import annotations

from typing import Optional

from kubernetes_tpu.metrics.registry import MetricsRegistry
from kubernetes_tpu.metrics.fabric_metrics import (
    _counter,
    _gauge,
    _histogram,
)

# device waits and dispatches are sub-second in steady state; the
# default bucket ladder starts at 1ms and tops out at 50s, fine here
_COMPILE_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 5.0, 10.0,
                    20.0, 40.0, 80.0)


class SolverMetrics:
    """Registered into the process default registry (legacyregistry
    pattern); reuses already-registered series so devprof and any tests
    constructing their own instance share state instead of clobbering."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        if registry is None:
            from kubernetes_tpu.metrics import default_registry

            registry = default_registry()
        self.registry = registry
        self.compiles_total = _counter(
            registry, "solver_compiles_total",
            "XLA compilations observed by the devprof compile listener, "
            "by padded-shape bucket (cache hits do not count — this is "
            "actual recompiles)",
            ("bucket",),
        )
        self.compile_seconds = _histogram(
            registry, "solver_compile_seconds",
            "Wall seconds spent in XLA backend compilation per solve "
            "cycle that compiled",
            buckets=_COMPILE_BUCKETS,
        )
        self.device_wait_seconds = _histogram(
            registry, "solver_device_wait_seconds",
            "block_until_ready wait per solve cycle: host wall time "
            "blocked on the device after dispatch (the streaming "
            "scheduler's double-buffer budget)",
        )
        self.dispatch_seconds = _histogram(
            registry, "solver_dispatch_seconds",
            "Async XLA dispatch time per solve cycle (solver call "
            "returning a lazy handle, before any block)",
        )
        self.pad_occupancy_ratio = _gauge(
            registry, "solver_pad_occupancy_ratio",
            "Real rows / padded rows of the last solve in each "
            "padded-shape bucket (1.0 = no device time wasted on pad)",
            ("bucket",),
        )
        self.transfer_bytes_total = _counter(
            registry, "solver_transfer_bytes_total",
            "Host-device transfer volume computed from the encoded "
            "plane shapes/dtypes, by direction (h2d = pod stream + "
            "static/state uploads, d2h = materialized assignments)",
            ("direction",),
        )
        self.unexpected_compiles_total = _counter(
            registry, "solver_unexpected_compiles_total",
            "Compilations that landed inside a MEASURED solve cycle "
            "(not warmup/pre-warm) — the forbidden case: thousands of "
            "pods absorbed the compile into their e2e latency; each "
            "increment also drops a flight-recorder dump",
        )


_default: Optional[SolverMetrics] = None


def solver_metrics() -> SolverMetrics:
    """Process-wide SolverMetrics bound to the default registry (the
    fabric_metrics pattern)."""
    global _default
    if _default is None:
        _default = SolverMetrics()
    return _default
