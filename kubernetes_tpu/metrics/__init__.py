from kubernetes_tpu.metrics.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from kubernetes_tpu.metrics.scheduler_metrics import SchedulerMetrics

# process-wide registry (reference component-base/metrics/legacyregistry):
# components register into this unless given their own; /metrics serves it
_default_registry = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _default_registry
