from kubernetes_tpu.metrics.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from kubernetes_tpu.metrics.scheduler_metrics import SchedulerMetrics
