"""API Priority & Fairness metrics (reference analogs:
``apiserver_flowcontrol_rejected_requests_total``,
``apiserver_flowcontrol_dispatched_requests_total``,
``apiserver_flowcontrol_current_executing_seats``,
``apiserver_flowcontrol_current_inqueue_requests``,
``apiserver_flowcontrol_request_queue_length_after_enqueue`` /
wait-duration histograms).

Operationally, three questions these answer:

- *who is being pushed back*: ``apf_rejected_requests_total
  {priority_level, reason}`` (queue-full | timeout | shed) — a climbing
  workload-level rate with a flat system-level rate is the subsystem
  working as designed; a climbing SYSTEM rate is an under-provisioned
  control plane;
- *is batching laundering concurrency*: ``apf_seats_dispatched_total /
  apf_dispatched_requests_total`` per level is the average request
  width — bulk-verb abuse shows up as width, not as extra requests;
- *how close to saturation*: ``apf_current_executing_seats`` vs
  ``apf_request_concurrency_limit`` per level, and the queue-wait
  histogram's tail.

``absorb_snapshot`` mirrors a REMOTE server's ``/debug/apf`` totals
into this process's counters, so the bench harness (apiserver in a
child process) can still emit the ``apf`` diag segment from the
scheduler process.
"""

from __future__ import annotations

from typing import Dict, Optional

from kubernetes_tpu.metrics.fabric_metrics import (
    _counter,
    _gauge,
    _histogram,
)
from kubernetes_tpu.metrics.registry import MetricsRegistry

_QUEUE_WAIT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                       1.0, 2.0, 5.0, 10.0)


class ApfMetrics:
    def __init__(self, registry: Optional[MetricsRegistry] = None):
        if registry is None:
            from kubernetes_tpu.metrics import default_registry

            registry = default_registry()
        self.registry = registry
        self.rejected_requests_total = _counter(
            registry, "apf_rejected_requests_total",
            "Requests rejected by API Priority & Fairness, by priority "
            "level and reason (queue-full, timeout, shed)",
            ("priority_level", "reason"),
        )
        self.dispatched_requests_total = _counter(
            registry, "apf_dispatched_requests_total",
            "Requests admitted to execute by APF, by priority level",
            ("priority_level",),
        )
        self.seats_dispatched_total = _counter(
            registry, "apf_seats_dispatched_total",
            "Seats (request width) admitted to execute by APF, by "
            "priority level — seats/requests is the average width, the "
            "bulk-verb concurrency-laundering detector",
            ("priority_level",),
        )
        self.current_executing_seats = _gauge(
            registry, "apf_current_executing_seats",
            "Seats currently occupied by executing requests, by "
            "priority level",
            ("priority_level",),
        )
        self.current_inqueue_requests = _gauge(
            registry, "apf_current_inqueue_requests",
            "Requests currently waiting in APF queues, by priority level",
            ("priority_level",),
        )
        self.peak_executing_seats = _gauge(
            registry, "apf_peak_executing_seats",
            "High-water mark of executing seats per priority level "
            "since the last diag read — bench rows consume (reset) it "
            "so each row reports its own peak, not the gauge's current "
            "post-run value (~0 once the row's requests drain)",
            ("priority_level",),
        )
        self.request_concurrency_limit = _gauge(
            registry, "apf_request_concurrency_limit",
            "Assured seat budget per priority level (shares of the "
            "legacy lane budgets)",
            ("priority_level",),
        )
        self.request_queue_wait_seconds = _histogram(
            registry, "apf_request_queue_wait_seconds",
            "Time requests spent queued before dispatch or rejection, "
            "by priority level",
            ("priority_level",),
            buckets=_QUEUE_WAIT_BUCKETS,
        )

    # the last absorbed /debug/apf snapshot, kept whole: the queue-wait
    # histogram and peak-seat numbers live server-side and cannot be
    # reconstructed from mirrored counters — bench.py's diag segment
    # reads them from here for remote-server rows
    last_snapshot: Optional[Dict] = None

    def absorb_snapshot(self, snap: Dict,
                        instance: Optional[str] = None) -> None:
        """Thin compat wrapper: fold a remote server's /debug/apf
        snapshot totals into this process's counters. Since the SLI
        layer landed, the generic path is ``metrics/federation.py``
        (scrape the child's /metrics, merge + fold EVERY counter family
        — no per-family mapping); this wrapper reshapes the legacy JSON
        snapshot into counter samples and routes them through the
        federation's delta ledger. With the default ``instance=None``
        it keeps the EXACT legacy contract — each call is a different
        server lifetime, so the full totals fold in (the ledger is
        forgotten first; two calls with the same totals double, as the
        old per-family inc did). Pass a stable ``instance`` to share
        the delta ledger with the scrape path instead, so repeated
        absorbs of the same still-running server never double-count."""
        from kubernetes_tpu.metrics.federation import metrics_federation

        self.last_snapshot = snap
        one_shot = instance is None
        if one_shot:
            instance = "debug-apf"
        rejected: Dict[tuple, float] = {}
        dispatched: Dict[tuple, float] = {}
        seats: Dict[tuple, float] = {}
        for name, lv in (snap.get("levels") or {}).items():
            for reason, n in (lv.get("rejected") or {}).items():
                if n:
                    rejected[(name, reason)] = float(n)
            if lv.get("dispatched_total"):
                dispatched[(name,)] = float(lv["dispatched_total"])
            if lv.get("seats_dispatched_total"):
                seats[(name,)] = float(lv["seats_dispatched_total"])
            if lv.get("capacity"):
                self.request_concurrency_limit.set(lv["capacity"], name)
        fed = metrics_federation()
        if one_shot:
            fed.forget_instance(instance)
        fed.fold_samples("apf_rejected_requests_total",
                         ("priority_level", "reason"), rejected, instance,
                         into=self.registry)
        fed.fold_samples("apf_dispatched_requests_total",
                         ("priority_level",), dispatched, instance,
                         into=self.registry)
        fed.fold_samples("apf_seats_dispatched_total",
                         ("priority_level",), seats, instance,
                         into=self.registry)


_default: Optional[ApfMetrics] = None


def apf_metrics() -> ApfMetrics:
    """Process-wide ApfMetrics bound to the default registry (the
    legacyregistry pattern fabric_metrics follows)."""
    global _default
    if _default is None:
        _default = ApfMetrics()
    return _default
