"""REST-fabric resilience metrics: the observability half of the
fault-injection / degraded-mode stack (reference analogs:
``rest_client_requests_total`` retry labels in component-base,
apiserver's ``apiserver_request_terminations_total``, and the
chaosmonkey suites' per-disruption accounting).

Three series matter operationally:

- ``client_retries_total{verb,reason}`` — every time a client re-issued
  a request after a transport drop, a 429/503 pushback, or a watch
  relist; a climbing rate under steady state means the fabric is sick.
- ``faults_injected_total{fault,resource}`` — counted by the server's
  FaultGate at injection time, so a chaos run can reconcile "faults
  thrown" against "retries absorbed".
- ``degraded_mode_seconds`` — cumulative wall-clock the scheduler spent
  with binding paused because its client's circuit breaker was open
  (plus a 0/1 ``degraded_mode`` gauge for live dashboards).

The node-churn resilience layer (harness/chaos_nodes.py) adds three:
``node_evictions_total{reason}`` (pods deleted off unreachable or
vanished nodes), ``stale_binds_rejected_total{path}`` (commit-time
guards refusing an assignment whose target node died, was cordoned, or
went unreachable between solve and commit), and ``pod_rescue_seconds``
(eviction → replacement-bound latency through the rescue pipeline).
"""

from __future__ import annotations

from typing import Optional

from kubernetes_tpu.metrics.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)


def _counter(registry: MetricsRegistry, name: str, help_text: str,
             labels=()) -> Counter:
    existing = registry.get(name)
    if isinstance(existing, Counter):
        return existing
    return registry.register(Counter(name, help_text, labels))


def _gauge(registry: MetricsRegistry, name: str, help_text: str,
           labels=()) -> Gauge:
    existing = registry.get(name)
    if isinstance(existing, Gauge):
        return existing
    return registry.register(Gauge(name, help_text, labels))


def _histogram(registry: MetricsRegistry, name: str, help_text: str,
               labels=(), buckets=None) -> Histogram:
    existing = registry.get(name)
    if isinstance(existing, Histogram):
        return existing
    if buckets is None:
        return registry.register(Histogram(name, help_text, labels))
    return registry.register(
        Histogram(name, help_text, labels, buckets=buckets))


class FabricMetrics:
    """Retry / fault / degraded-mode counters. Reuses already-registered
    metrics so the server's gate and any number of clients in one
    process share series instead of clobbering each other."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        if registry is None:
            from kubernetes_tpu.metrics import default_registry

            registry = default_registry()
        self.registry = registry
        self.client_retries_total = _counter(
            registry, "client_retries_total",
            "Requests re-issued by REST clients, by verb and reason "
            "(transport, http_429, http_503, relist)",
            ("verb", "reason"),
        )
        self.faults_injected_total = _counter(
            registry, "faults_injected_total",
            "Wire faults injected by the apiserver FaultGate, by fault "
            "type and resource",
            ("fault", "resource"),
        )
        self.degraded_mode_seconds = _counter(
            registry, "degraded_mode_seconds",
            "Cumulative seconds the scheduler spent in degraded mode "
            "(binding paused, circuit breaker open)",
        )
        self.degraded_mode = _gauge(
            registry, "degraded_mode",
            "1 while the scheduler's client circuit breaker is open",
        )
        self.client_relists_total = _counter(
            registry, "client_relists_total",
            "Full relists performed by watch clients after a dropped "
            "stream or an expired resourceVersion",
            ("kind",),
        )
        # -- node-churn resilience (harness/chaos_nodes.py) ------------
        self.node_evictions_total = _counter(
            registry, "node_evictions_total",
            "Pods evicted off dead nodes, by reason (unreachable = "
            "nodelifecycle grace expiry, orphaned = pod bound to a "
            "node that no longer exists)",
            ("reason",),
        )
        self.stale_binds_rejected_total = _counter(
            registry, "stale_binds_rejected_total",
            "Solved assignments refused at commit time because the "
            "target node was deleted, cordoned, or unreachable-tainted "
            "between snapshot and commit, by rejecting path "
            "(batch = sidecar pre-commit, bulk = commit_assignments_bulk, "
            "serial = per-pod commit)",
            ("path",),
        )
        self.pod_rescue_seconds = _histogram(
            registry, "pod_rescue_seconds",
            "Eviction-to-rescheduled latency: time from a workload pod "
            "being deleted off a dead node to its replacement being "
            "bound somewhere live",
        )


_default: Optional[FabricMetrics] = None


def fabric_metrics() -> FabricMetrics:
    """Process-wide FabricMetrics bound to the default registry (the
    legacyregistry pattern scheduler_metrics already follows)."""
    global _default
    if _default is None:
        _default = FabricMetrics()
    return _default
