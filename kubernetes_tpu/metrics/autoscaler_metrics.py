"""Cluster-autoscaler metrics (the observability half of
``kubernetes_tpu/autoscaler/``; reference analogs:
``cluster_autoscaler_scaled_up_nodes_total``,
``cluster_autoscaler_unschedulable_pods_count``, and the
``function_duration_seconds{function=scaleUp}`` family in
cluster-autoscaler/metrics).

Four series matter operationally:

- ``autoscaler_scaleups_total{group,expander}`` — nodes provisioned per
  scale-up decision, by chosen node group and the expander strategy
  that chose it;
- ``autoscaler_scaledowns_total{group}`` — nodes drained and deleted;
- ``autoscaler_pending_unschedulable`` — the live size of the trigger
  surface (queue leftovers + FailedScheduling outcomes); a gauge stuck
  above zero with no scale-ups means every group is at max or the
  pending pods fit no template;
- ``autoscaler_time_to_capacity_seconds`` — pending-set-first-seen →
  pending-set-drained latency, the elastic bench's headline histogram.
"""

from __future__ import annotations

from typing import Optional

from kubernetes_tpu.metrics.fabric_metrics import (
    _counter,
    _gauge,
    _histogram,
)
from kubernetes_tpu.metrics.registry import MetricsRegistry


class AutoscalerMetrics:
    """Scale-up / scale-down / pending counters. Reuses already-
    registered metrics so the control loop and any in-process readers
    share series (the FabricMetrics pattern)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        if registry is None:
            from kubernetes_tpu.metrics import default_registry

            registry = default_registry()
        self.registry = registry
        self.scaleups_total = _counter(
            registry, "autoscaler_scaleups_total",
            "Nodes provisioned by cluster-autoscaler scale-up decisions, "
            "by node group and expander strategy",
            ("group", "expander"),
        )
        self.scaledowns_total = _counter(
            registry, "autoscaler_scaledowns_total",
            "Nodes drained and deleted by cluster-autoscaler scale-down, "
            "by node group",
            ("group",),
        )
        self.pending_unschedulable = _gauge(
            registry, "autoscaler_pending_unschedulable",
            "Pods currently in the autoscaler's unschedulable trigger "
            "set (queue leftovers + FailedScheduling outcomes)",
        )
        self.time_to_capacity_seconds = _histogram(
            registry, "autoscaler_time_to_capacity_seconds",
            "Latency from a pending unschedulable set first appearing "
            "to that set draining to zero (capacity arrived and every "
            "triggering pod bound or went away)",
            # capacity acquisition spans instance boot times, not
            # request latencies: the default 50s ceiling would clamp
            # the headline elastic row's p99 (30k-pod bursts legally
            # take minutes)
            buckets=(0.5, 1, 2, 5, 10, 20, 30, 60, 120, 300, 600,
                     1200, 1800),
        )
        self.evicted_for_scaledown_total = _counter(
            registry, "autoscaler_evicted_for_scaledown_total",
            "Pods evicted (PDB-respecting) while draining a scale-down "
            "candidate node",
        )


_default: Optional[AutoscalerMetrics] = None


def autoscaler_metrics() -> AutoscalerMetrics:
    """Process-wide AutoscalerMetrics bound to the default registry
    (the legacyregistry pattern fabric_metrics follows)."""
    global _default
    if _default is None:
        _default = AutoscalerMetrics()
    return _default
