"""Scheduler metric definitions (reference
``pkg/scheduler/metrics/metrics.go:42-159``): e2e scheduling latency,
per-attempt latency, framework extension-point durations, queue incoming
counters, pending gauges, preemption counters — the set the perf harness
scrapes (scheduler_perf_test.go:50-58)."""

from __future__ import annotations

from kubernetes_tpu.metrics.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)


class SchedulerMetrics:
    def __init__(self, registry: MetricsRegistry = None):
        self.registry = registry or MetricsRegistry()
        r = self.registry
        self.e2e_scheduling_duration = r.register(
            Histogram(
                "scheduler_e2e_scheduling_duration_seconds",
                "E2e scheduling latency (scheduling algorithm + binding)",
                ("result",),
            )
        )
        self.scheduling_algorithm_duration = r.register(
            Histogram(
                "scheduler_scheduling_algorithm_duration_seconds",
                "Scheduling algorithm latency",
            )
        )
        self.pod_scheduling_duration = r.register(
            Histogram(
                "scheduler_pod_scheduling_duration_seconds",
                "E2e latency for a pod being scheduled, from first attempt",
                ("attempts",),
            )
        )
        self.pod_scheduling_attempts = r.register(
            Histogram(
                "scheduler_pod_scheduling_attempts",
                "Number of attempts to successfully schedule a pod",
                buckets=(1, 2, 4, 8, 16),
            )
        )
        self.schedule_attempts = r.register(
            Counter(
                "scheduler_schedule_attempts_total",
                "Number of attempts to schedule pods, by result",
                ("result", "profile"),
            )
        )
        self.framework_extension_point_duration = r.register(
            Histogram(
                "scheduler_framework_extension_point_duration_seconds",
                "Latency for running all plugins of a specific extension point",
                ("extension_point", "status", "profile"),
            )
        )
        self.queue_incoming_pods = r.register(
            Counter(
                "scheduler_queue_incoming_pods_total",
                "Number of pods added to scheduling queues by event and queue type",
                ("queue", "event"),
            )
        )
        self.pending_pods = r.register(
            Gauge(
                "scheduler_pending_pods",
                "Number of pending pods by queue",
                ("queue",),
            )
        )
        self.preemption_attempts = r.register(
            Counter(
                "scheduler_preemption_attempts_total",
                "Total preemption attempts in the cluster",
            )
        )
        self.preemption_victims = r.register(
            Histogram(
                "scheduler_preemption_victims",
                "Number of selected preemption victims",
                buckets=(1, 2, 4, 8, 16, 32, 64),
            )
        )
        self.cache_size = r.register(
            Gauge(
                "scheduler_scheduler_cache_size",
                "Number of nodes, pods, and assumed pods in the cache",
                ("type",),
            )
        )
        self.goroutines = r.register(
            Gauge(
                "scheduler_scheduler_goroutines",
                "Number of running binding goroutine-equivalents",
                ("work",),
            )
        )
        self.batch_solve_duration = r.register(
            Histogram(
                "scheduler_tpu_batch_solve_duration_seconds",
                "Device batch-solve latency (TPU path only)",
                ("phase",),
            )
        )

    # hooks used by framework/queue --------------------------------------
    def observe_extension_point(self, point: str, status: str, seconds: float,
                                profile: str = "") -> None:
        self.framework_extension_point_duration.observe(
            seconds, point, status, profile
        )

    def pods_added(self, queue: str, event: str, amount: float = 1.0) -> None:
        self.queue_incoming_pods.inc(queue, event, amount=amount)

    def pods_moved(self, event: str) -> None:
        self.queue_incoming_pods.inc("active_or_backoff", event)
