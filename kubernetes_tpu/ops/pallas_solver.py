"""Pallas TPU kernel for the batch scheduling solve.

The XLA ``lax.scan`` path (``ops.solver``) pays per-step dispatch and
HBM round-trips for every pod: each scan iteration re-reads and
re-writes the full cluster state from HBM. This kernel runs the WHOLE
pod loop inside one ``pallas_call`` with the cluster state resident in
VMEM, so a pod step touches on-chip memory only (~100KB of state), and
the per-pod cost drops from ~100µs to single-digit µs.

Key design points (see ``/opt/skills/guides/pallas_guide.md``):

- **Node-axis layout**: every per-node array is shaped ``[.., NB, 128]``
  (``NB = N/128`` sublane groups × 128 lanes) so elementwise work runs
  full-width on the VPU.
- **No gathers**: the scan path's ``take_along_axis`` (counts per
  topology value, indexed by each node's value code) is a gather — slow
  or unsupported in Mosaic. Instead the kernel keeps topology counts
  PER NODE (``counts_node[sc, n]`` = matching pods in node *n*'s domain
  value). A commit to node *j* updates all nodes in *j*'s domain with
  one vector compare (``codes[sc] == code_j``), which is exactly the
  domain-value increment of the reference semantics
  (``podtopologyspread/filtering.go:313-324``) — duplicated per member
  node, which min/compare reductions are insensitive to.
- **State carry**: dynamic state buffers are aliased input→output
  (``input_output_aliases``), so the session keeps them on device
  between batches, like the XLA path's carried ``_State``.
- Semantics mirror ``ops.solver._step`` one-to-one; the differential
  tests assert equal assignments against ``solve_scan``.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.ops.encode import EncodedBatch, EncodedCluster
from kubernetes_tpu.ops.solver import NEG_INF, SolverParams

LANES = 128
POD_SUB = 8   # pods per grid step (SMEM sublane tiling)
BIG_I32 = np.int32(2**30)


class PStatic(NamedTuple):
    """Solve-invariant arrays in kernel layout (device-resident)."""

    ints: jnp.ndarray        # [C_s, NB, 128] int32 — stacked int planes
    f32s: jnp.ndarray        # [U, NB, 128] float32 — static scores
    sc_meta: jnp.ndarray     # [2, SC] int32 (SMEM): max_skew row, hard row
    # static dims (Python ints — part of the compile key)
    r: int
    sc: int
    t: int
    u: int
    v: int
    nb: int
    sv: int = 0   # shared-volume attach plane count (0 = no sv planes)


class PState(NamedTuple):
    """Dynamic state in kernel layout: ONE stacked int32 array so the
    carry is a single device buffer. Plane order:
    requested[R] | nonzero[2] | pod_count | sc_counts[SC] |
    term_counts[T] | term_owners[T] | term_totals (lane t = total)."""

    planes: jnp.ndarray      # [C_d, NB, 128] int32


def _static_planes(r: int, sc: int, t: int, u: int):
    """Plane offsets inside PStatic.ints."""
    o = {}
    i = 0
    o["alloc"] = i; i += r
    o["max_pods"] = i; i += 1
    o["masks"] = i; i += u          # static predicate masks (0/1)
    o["sc_codes"] = i; i += sc
    o["sc_domain"] = i; i += u * sc  # per-profile eligible-domain masks
    o["term_codes"] = i; i += t
    o["node_valid"] = i; i += 1
    return o, i


def _state_planes(r: int, sc: int, t: int, sv: int = 0):
    o = {}
    i = 0
    o["requested"] = i; i += r
    o["nonzero"] = i; i += 2
    o["pod_count"] = i; i += 1
    o["sc_counts"] = i; i += sc
    o["term_counts"] = i; i += t
    o["term_owners"] = i; i += t
    # shared-volume attach planes (0/1 per node), sv = 0 for epochs
    # without shared CSI volumes — the layout is then bit-identical to
    # the pre-sv contract and no executable recompiles
    o["sv_attached"] = i; i += sv
    o["totals"] = i; i += 1          # lane t holds term t's real-column total
    return o, i


def _to_planes(arr: np.ndarray, nb: int) -> np.ndarray:
    """[K, N] -> [K, NB, 128]."""
    k = arr.shape[0]
    return np.ascontiguousarray(arr.reshape(k, nb, LANES))


def prepare(cluster: EncodedCluster, batch: EncodedBatch,
            device: bool = True) -> Tuple[PStatic, PState]:
    """Host-side packing of the encoder output into kernel layout.
    ``device=False`` keeps the planes as host numpy arrays (the native
    C++ backend mutates them in place through ctypes)."""
    n = cluster.allocatable.shape[0]
    if n % LANES != 0:
        raise ValueError(f"padded node count {n} not a multiple of {LANES}")
    nb = n // LANES
    r = cluster.allocatable.shape[1]
    scn = batch.sc_counts.shape[0]
    tn = batch.term_counts.shape[0]
    u = batch.static_masks.shape[0]
    v = batch.num_values

    sc_codes = np.minimum(
        cluster.topo_codes[:, batch.sc_key_idx].T, v
    ).astype(np.int32)                                        # [SC, N]
    term_codes = np.minimum(
        cluster.topo_codes[:, batch.term_key_idx].T, v
    ).astype(np.int32)                                        # [T, N]
    node_valid = np.zeros(n, dtype=np.int32)
    node_valid[: cluster.num_real_nodes] = 1

    # per-node eligible-domain masks: domain_node[u, sc, n] =
    # sc_domain[u, sc, code(sc, n)]  (sentinel column V is always False)
    dom_node = np.take_along_axis(
        batch.sc_domain.astype(np.int32),                     # [U, SC, V+1]
        sc_codes[None, :, :],                                 # [1, SC, N]
        axis=2,
    )                                                         # [U, SC, N]

    so, cs = _static_planes(r, scn, tn, u)
    ints = np.zeros((cs, n), dtype=np.int32)
    ints[so["alloc"]:so["alloc"] + r] = cluster.allocatable.T
    ints[so["max_pods"]] = cluster.max_pods
    ints[so["masks"]:so["masks"] + u] = batch.static_masks.astype(np.int32)
    ints[so["sc_codes"]:so["sc_codes"] + scn] = sc_codes
    ints[so["sc_domain"]:so["sc_domain"] + u * scn] = dom_node.reshape(
        u * scn, n
    )
    ints[so["term_codes"]:so["term_codes"] + tn] = term_codes
    ints[so["node_valid"]] = node_valid

    sc_meta = np.stack(
        [batch.sc_max_skew.astype(np.int32),
         batch.sc_hard.astype(np.int32)]
    )                                                         # [2, SC]

    put = jax.device_put if device else (lambda a: a)
    svn = 0 if cluster.sv_attached is None else cluster.sv_attached.shape[0]
    pstatic = PStatic(
        ints=put(_to_planes(ints, nb)),
        f32s=put(_to_planes(batch.static_scores.astype(np.float32), nb)),
        sc_meta=put(sc_meta),
        r=r, sc=scn, t=tn, u=u, v=v, nb=nb, sv=svn,
    )
    pstate = prepare_state(cluster, batch, device=device)
    return pstatic, pstate


def prepare_state(cluster: EncodedCluster, batch: EncodedBatch,
                  device: bool = True) -> PState:
    """The DYNAMIC half of ``prepare`` alone: per-node requested /
    nonzero / pod-count planes plus the topology/affinity count planes.
    Used by the session's state-only rebuild — after self-inflicted
    cache mutations whose static planes are bit-identical (e.g. mass
    preemption: victims change only the dynamic state), re-uploading
    just these planes skips the static upload and its host packing."""
    n = cluster.allocatable.shape[0]
    nb = n // LANES
    r = cluster.allocatable.shape[1]
    scn = batch.sc_counts.shape[0]
    tn = batch.term_counts.shape[0]
    v = batch.num_values
    sc_codes = np.minimum(
        cluster.topo_codes[:, batch.sc_key_idx].T, v
    ).astype(np.int32)
    term_codes = np.minimum(
        cluster.topo_codes[:, batch.term_key_idx].T, v
    ).astype(np.int32)

    svn = 0 if cluster.sv_attached is None else cluster.sv_attached.shape[0]
    # dynamic state: counts translated to the per-node representation
    do, cd = _state_planes(r, scn, tn, svn)
    planes = np.zeros((cd, n), dtype=np.int32)
    planes[do["requested"]:do["requested"] + r] = cluster.requested.T
    planes[do["nonzero"]:do["nonzero"] + 2] = cluster.nonzero_requested.T
    planes[do["pod_count"]] = cluster.pod_count
    planes[do["sc_counts"]:do["sc_counts"] + scn] = np.take_along_axis(
        batch.sc_counts, sc_codes, axis=1
    )
    planes[do["term_counts"]:do["term_counts"] + tn] = np.take_along_axis(
        batch.term_counts, term_codes, axis=1
    )
    planes[do["term_owners"]:do["term_owners"] + tn] = np.take_along_axis(
        batch.term_owners, term_codes, axis=1
    )
    if svn:
        planes[do["sv_attached"]:do["sv_attached"] + svn] = \
            cluster.sv_attached
    if tn > n:
        raise ValueError(
            f"planes layout holds per-term totals in one node-sized plane "
            f"({n}); {tn} tracked terms exceed it — use the legacy backend"
        )
    totals = np.zeros(n, dtype=np.int32)
    totals[:tn] = batch.term_counts[:, :v].sum(axis=1)
    planes[do["totals"]] = totals

    put = jax.device_put if device else (lambda a: a)
    return PState(planes=put(_to_planes(planes, nb)))


# ----------------------------------------------------------------------
def _kernel(params: SolverParams, r: int, scn: int, tn: int, u: int,
            v: int, nb: int, b: int,
            # inputs (state_in_ref is the alias source — outputs are used)
            sc_meta_ref, ints_ref, floats_ref, static_ref, scores_ref,
            state_in_ref,
            # outputs (state_ref aliases state_in_ref's buffer)
            assign_ref, state_ref,
            # scratch: per-term real-column totals (scalars must live in
            # SMEM — Mosaic cannot store scalars to VMEM)
            totals_ref):
    from jax.experimental import pallas as pl

    so, _ = _static_planes(r, scn, tn, u)
    do, _ = _state_planes(r, scn, tn)
    step = pl.program_id(0)

    # static per-node planes (VMEM reads, hoisted by Mosaic where possible)
    node_valid = static_ref[so["node_valid"]] > 0
    flat_idx = (
        jax.lax.broadcasted_iota(jnp.int32, (nb, LANES), 0) * LANES
        + jax.lax.broadcasted_iota(jnp.int32, (nb, LANES), 1)
    )
    lane_row = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)

    # totals plane -> SMEM scalars on the first step (the running totals
    # live in SMEM scratch, which persists across the sequential grid;
    # Mosaic cannot store scalars to VMEM)
    @pl.when(step == 0)
    def _init_totals():
        totals_plane = state_ref[do["totals"]][0:1, :]
        for _ti in range(tn):
            totals_ref[_ti] = jnp.sum(
                jnp.where(lane_row == _ti, totals_plane, 0)
            )

    # packed pod-stream column offsets (pack_podin layout)
    c_req = 0
    c_nonzero = r
    c_profile = r + 2
    c_valid = r + 3
    c_pod_sc = r + 4
    c_sc_match = r + 4 + scn
    c_match_by = r + 4 + 2 * scn
    c_own_aff = r + 4 + 2 * scn + tn
    c_own_anti = r + 4 + 2 * scn + 2 * tn

    for sub in range(POD_SUB):  # 8 pods per grid step (SMEM tiling rule)
        pod_valid = ints_ref[sub, c_valid] > 0
        profile = ints_ref[sub, c_profile]

        # ---- feasibility ------------------------------------------------
        fit = node_valid & (
            state_ref[do["pod_count"]] < static_ref[so["max_pods"]]
        )
        for ri in range(r):
            req_r = ints_ref[sub, c_req + ri]
            fit &= (
                state_ref[do["requested"] + ri] + req_r
                <= static_ref[so["alloc"] + ri]
            )
        static_ok = static_ref[so["masks"] + profile] > 0
        feasible = fit & static_ok & pod_valid

        # topology spread (hard)
        for sci in range(scn):
            pod_has = ints_ref[sub, c_pod_sc + sci] > 0
            hard = sc_meta_ref[1, sci] > 0
            active = pod_has & hard
            self_match = ints_ref[sub, c_sc_match + sci]
            counts = state_ref[do["sc_counts"] + sci]
            codes = static_ref[so["sc_codes"] + sci]
            missing = codes >= v
            dom = static_ref[so["sc_domain"] + profile * scn + sci] > 0
            min_c = jnp.min(jnp.where(dom, counts, BIG_I32))
            min_c = jnp.where(jnp.any(dom), min_c, 0)
            skew = counts + self_match - min_c
            ok = ~(missing | (skew > sc_meta_ref[0, sci]))
            # select on i1 vectors does not lower in Mosaic; use logic
            feasible &= ~active | ok

        # inter-pod affinity
        has_aff = False
        aff_sat = jnp.ones((nb, LANES), dtype=jnp.bool_)
        no_any = True
        self_all = True
        for ti in range(tn):
            codes = static_ref[so["term_codes"] + ti]
            t_missing = codes >= v
            tcounts = state_ref[do["term_counts"] + ti]
            towners = state_ref[do["term_owners"] + ti]
            matched = ints_ref[sub, c_match_by + ti] > 0
            own_aff = ints_ref[sub, c_own_aff + ti] > 0
            own_anti = ints_ref[sub, c_own_anti + ti] > 0
            feasible &= ~(matched & (towners > 0))        # existing anti
            feasible &= ~(own_anti & (tcounts > 0))       # own anti
            aff_here = (tcounts > 0) & ~t_missing
            aff_sat &= ~own_aff | aff_here
            total_t = totals_ref[ti]
            no_any &= ~own_aff | (total_t == 0)
            self_all &= ~own_aff | matched
            has_aff |= own_aff
        aff_ok = ~has_aff | aff_sat | (no_any & self_all)
        feasible &= aff_ok

        # ---- scores -----------------------------------------------------
        alloc_cpu = jnp.maximum(static_ref[so["alloc"]], 1).astype(jnp.float32)
        alloc_mem = jnp.maximum(
            static_ref[so["alloc"] + 1], 1
        ).astype(jnp.float32)
        nz_cpu = ints_ref[sub, c_nonzero]
        nz_mem = ints_ref[sub, c_nonzero + 1]
        cpu_frac = (
            state_ref[do["nonzero"]] + nz_cpu
        ).astype(jnp.float32) / alloc_cpu
        mem_frac = (
            state_ref[do["nonzero"] + 1] + nz_mem
        ).astype(jnp.float32) / alloc_mem
        over = (cpu_frac >= 1.0) | (mem_frac >= 1.0)
        balanced = jnp.where(
            over, 0.0, (1.0 - jnp.abs(cpu_frac - mem_frac)) * 100.0
        )
        least = (
            jnp.clip(1.0 - cpu_frac, 0.0, 1.0)
            + jnp.clip(1.0 - mem_frac, 0.0, 1.0)
        ) * 50.0

        soft_counts = jnp.zeros((nb, LANES), dtype=jnp.float32)
        any_soft = False
        for sci in range(scn):
            pod_has = ints_ref[sub, c_pod_sc + sci] > 0
            soft = ~(sc_meta_ref[1, sci] > 0) & pod_has
            soft_counts += jnp.where(
                soft, state_ref[do["sc_counts"] + sci], 0
            ).astype(jnp.float32)
            any_soft |= soft
        spread_score = jnp.where(any_soft, 100.0 / (1.0 + soft_counts), 0.0)

        pref_score = jnp.zeros((nb, LANES), dtype=jnp.float32)
        for ti in range(tn):
            w = floats_ref[sub, ti]
            pref_score += w * state_ref[do["term_counts"] + ti].astype(
                jnp.float32
            )

        score = (
            params.balanced_weight * balanced
            + params.least_weight * least
            + params.spread_weight * spread_score
            + params.affinity_weight * pref_score
            + params.static_weight * scores_ref[profile]
        )
        score = jnp.where(feasible, score, NEG_INF)

        # ---- argmax (lowest index wins ties) ---------------------------
        mx = jnp.max(score)
        found = mx > NEG_INF / 2
        cand = jnp.where(feasible & (score >= mx), flat_idx, BIG_I32)
        chosen = jnp.min(cand)
        valid = found & pod_valid
        assign_ref[sub, 0] = jnp.where(found, chosen, -1)

        # ---- commit -----------------------------------------------------
        onehot = (flat_idx == chosen) & valid
        inc = onehot.astype(jnp.int32)
        for ri in range(r):
            state_ref[do["requested"] + ri] += inc * ints_ref[sub, c_req + ri]
        state_ref[do["nonzero"]] += inc * nz_cpu
        state_ref[do["nonzero"] + 1] += inc * nz_mem
        state_ref[do["pod_count"]] += inc

        valid_i = valid.astype(jnp.int32)
        for sci in range(scn):
            codes = static_ref[so["sc_codes"] + sci]
            code_j = jnp.sum(jnp.where(onehot, codes, 0))
            self_match = ints_ref[sub, c_sc_match + sci] * valid_i
            state_ref[do["sc_counts"] + sci] += (
                (codes == code_j).astype(jnp.int32) * self_match
            )
        for ti in range(tn):
            codes = static_ref[so["term_codes"] + ti]
            code_j = jnp.sum(jnp.where(onehot, codes, 0))
            same = (codes == code_j).astype(jnp.int32)
            matched = ints_ref[sub, c_match_by + ti] * valid_i
            own_anti = ints_ref[sub, c_own_anti + ti] * valid_i
            state_ref[do["term_counts"] + ti] += same * matched
            state_ref[do["term_owners"] + ti] += same * own_anti
            # real-column total: only counted when the chosen node's
            # domain value is real (code_j < v), matching the scan path's
            # exclusion of the sentinel column
            real = (code_j < v).astype(jnp.int32)
            totals_ref[ti] = totals_ref[ti] + matched * real

    # SMEM totals -> state plane on the last step (vector store), so the
    # carried state round-trips through the aliased output buffer
    @pl.when(step == (b // POD_SUB) - 1)
    def _flush_totals():
        row0 = jax.lax.broadcasted_iota(jnp.int32, (nb, LANES), 0) == 0
        lane2d = jax.lax.broadcasted_iota(jnp.int32, (nb, LANES), 1)
        plane = jnp.zeros((nb, LANES), dtype=jnp.int32)
        for _ti in range(tn):
            plane += jnp.where(
                row0 & (lane2d == _ti), totals_ref[_ti], 0
            )
        state_ref[do["totals"]] = plane


@functools.lru_cache(maxsize=64)
def _get_call(params: SolverParams, r: int, sc: int, t: int, u: int,
              v: int, nb: int, b: int, c_cols: int, t_cols: int,
              cd: int, interpret: bool):
    """Build and jit-wrap the pallas_call for one shape signature.
    Without the jit wrapper every invocation re-traces and re-lowers the
    kernel (≈1.6s fixed cost per call over the TPU tunnel); cached, the
    steady-state call is a single executable launch."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    kernel = functools.partial(_kernel, params, r, sc, t, u, v, nb, b)
    planes_shape = (cd, nb, LANES)
    ints_shape = (_static_planes(r, sc, t, u)[1], nb, LANES)
    f32s_shape = (u, nb, LANES)
    # Eight pods per grid step: the TPU grid is a sequential loop, so state
    # mutation across steps is ordered. The pod stream is block-mapped 8
    # ROWS per step into SMEM (scalar memory — the kernel consumes pod
    # fields as scalars with static offsets); the big per-node planes use
    # constant index maps so they stay resident in VMEM for the whole run.
    if b % POD_SUB != 0:
        raise ValueError(f"batch {b} not a multiple of {POD_SUB}")
    call = pl.pallas_call(
        kernel,
        grid=(b // POD_SUB,),
        in_specs=[
            pl.BlockSpec((2, sc), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),            # sc_meta
            pl.BlockSpec((POD_SUB, c_cols), lambda i: (i, 0),
                         memory_space=pltpu.SMEM),            # pod ints rows
            pl.BlockSpec((POD_SUB, t_cols), lambda i: (i, 0),
                         memory_space=pltpu.SMEM),            # pod floats rows
            pl.BlockSpec(ints_shape, lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),            # static ints
            pl.BlockSpec(f32s_shape, lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),            # static scores
            pl.BlockSpec(planes_shape, lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),            # state (in)
        ],
        out_specs=(
            pl.BlockSpec((POD_SUB, 1), lambda i: (i, 0),
                         memory_space=pltpu.SMEM),            # assignments
            pl.BlockSpec(planes_shape, lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),            # state (out)
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b, 1), jnp.int32),
            jax.ShapeDtypeStruct(planes_shape, jnp.int32),
        ),
        input_output_aliases={5: 1},   # state planes in -> out
        scratch_shapes=[pltpu.SMEM((max(t, 1),), jnp.int32)],
        interpret=interpret,
    )
    return jax.jit(call)


def _run(params: SolverParams, pstatic: PStatic, pstate: PState,
         pod_ints, pod_floats, interpret: bool):
    b = pod_ints.shape[0]
    if b % POD_SUB != 0:
        raise ValueError(f"batch {b} not a multiple of {POD_SUB}")
    call = _get_call(
        params, pstatic.r, pstatic.sc, pstatic.t, pstatic.u, pstatic.v,
        pstatic.nb, b, pod_ints.shape[1], pod_floats.shape[1],
        pstate.planes.shape[0], interpret,
    )
    assignments, new_planes = call(
        pstatic.sc_meta, pod_ints, pod_floats, pstatic.ints, pstatic.f32s,
        pstate.planes,
    )
    return assignments, PState(planes=new_planes)


# ----------------------------------------------------------------------
# Gather-free XLA scan over the SAME planes layout. The legacy scan
# (ops.solver._step) indexes per-value count tables with
# take_along_axis — a [T, N] gather per step that collapses at
# hostname-keyed terms (V≈N): ~18ms/step at T=100, V=5000. This variant
# keeps counts per node (like the kernel) so every op is a dense
# vector compare/add that XLA fuses, and it is vectorized over the
# SC/T axes — no Python unrolling — so it covers the wide constraint
# spaces the pallas kernel cannot.

@functools.partial(
    jax.jit, static_argnames=("params", "r", "sc", "t", "u", "v", "sv")
)
def _xla_planes_solve(params: SolverParams, r: int, sc: int, t: int,
                      u: int, v: int, sc_meta, static_ints, static_f32s,
                      planes, pod_ints, pod_floats, sv: int = 0):
    so, _ = _static_planes(r, sc, t, u)
    do, cd = _state_planes(r, sc, t, sv)
    nb, lanes = planes.shape[1], planes.shape[2]

    node_valid = static_ints[so["node_valid"]] > 0
    alloc = static_ints[so["alloc"]:so["alloc"] + r]
    max_pods = static_ints[so["max_pods"]]
    masks = static_ints[so["masks"]:so["masks"] + u]
    sc_codes = static_ints[so["sc_codes"]:so["sc_codes"] + sc]
    dom_all = static_ints[so["sc_domain"]:so["sc_domain"] + u * sc].reshape(
        u, sc, nb, lanes
    )
    term_codes = static_ints[so["term_codes"]:so["term_codes"] + t]
    sc_missing = sc_codes >= v
    t_missing = term_codes >= v
    flat_idx = (
        jax.lax.broadcasted_iota(jnp.int32, (nb, lanes), 0) * lanes
        + jax.lax.broadcasted_iota(jnp.int32, (nb, lanes), 1)
    )
    max_skew = sc_meta[0]
    hard = sc_meta[1] > 0

    # pod-stream column offsets (pack_podin layout)
    c_req, c_nonzero, c_profile, c_valid = 0, r, r + 2, r + 3
    c_pod_sc, c_sc_match = r + 4, r + 4 + sc
    c_match_by, c_own_aff, c_own_anti = (
        r + 4 + 2 * sc, r + 4 + 2 * sc + t, r + 4 + 2 * sc + 2 * t,
    )
    c_sv = r + 4 + 2 * sc + 3 * t   # (slot, attach col), sv epochs only

    def step(carry, pod):
        state, totals = carry
        row, pref_w = pod
        pod_valid = row[c_valid] > 0
        profile = row[c_profile]
        req = row[c_req:c_req + r]
        pod_sc = row[c_pod_sc:c_pod_sc + sc] > 0
        sc_match = row[c_sc_match:c_sc_match + sc] > 0
        match_by = row[c_match_by:c_match_by + t] > 0
        own_aff = row[c_own_aff:c_own_aff + t] > 0
        own_anti = row[c_own_anti:c_own_anti + t] > 0

        requested = state[do["requested"]:do["requested"] + r]
        fit = jnp.all(requested + req[:, None, None] <= alloc, axis=0)
        fit &= state[do["pod_count"]] < max_pods
        if sv:
            # shared-volume attach: demand is CONDITIONAL per node —
            # 1 only where this pod's shared volume isn't attached yet
            # (csi.go len(in_use | wanted) set semantics)
            sv_planes = state[do["sv_attached"]:do["sv_attached"] + sv]
            sv_slot = row[c_sv]
            sv_col = row[c_sv + 1]
            sv_is_shared = sv_slot < sv
            slot_c = jnp.minimum(sv_slot, sv - 1)
            att = jnp.take(sv_planes, slot_c, axis=0)      # [nb, lanes]
            sv_demand = jnp.where(sv_is_shared, 1 - att, 0)
            col_alloc = jnp.take(alloc, sv_col, axis=0)
            col_req = jnp.take(requested, sv_col, axis=0)
            col_pod = jnp.take(req, sv_col)
            fit &= col_req + col_pod + sv_demand <= col_alloc
        static_ok = masks[profile] > 0

        counts = state[do["sc_counts"]:do["sc_counts"] + sc]
        dom = dom_all[profile] > 0
        min_c = jnp.min(jnp.where(dom, counts, BIG_I32), axis=(1, 2))
        min_c = jnp.where(jnp.any(dom, axis=(1, 2)), min_c, 0)
        skew = counts + sc_match[:, None, None] - min_c[:, None, None]
        active_hard = pod_sc & hard
        spread_violation = jnp.any(
            active_hard[:, None, None]
            & ((skew > max_skew[:, None, None]) | sc_missing),
            axis=0,
        )

        tcounts = state[do["term_counts"]:do["term_counts"] + t]
        towners = state[do["term_owners"]:do["term_owners"] + t]
        existing_anti = jnp.any(
            match_by[:, None, None] & (towners > 0), axis=0
        )
        own_anti_block = jnp.any(
            own_anti[:, None, None] & (tcounts > 0), axis=0
        )
        aff_here = (tcounts > 0) & ~t_missing
        aff_sat = jnp.all(~own_aff[:, None, None] | aff_here, axis=0)
        no_any = jnp.all(~own_aff | (totals == 0))
        self_all = jnp.all(~own_aff | match_by)
        has_aff = jnp.any(own_aff)
        aff_ok = ~has_aff | aff_sat | (no_any & self_all)

        feasible = (
            node_valid & static_ok & fit & ~spread_violation
            & ~existing_anti & ~own_anti_block & aff_ok & pod_valid
        )

        alloc_cpu = jnp.maximum(alloc[0], 1).astype(jnp.float32)
        alloc_mem = jnp.maximum(alloc[1], 1).astype(jnp.float32)
        nz = state[do["nonzero"]:do["nonzero"] + 2]
        cpu_frac = (nz[0] + row[c_nonzero]).astype(jnp.float32) / alloc_cpu
        mem_frac = (nz[1] + row[c_nonzero + 1]).astype(
            jnp.float32
        ) / alloc_mem
        over = (cpu_frac >= 1.0) | (mem_frac >= 1.0)
        balanced = jnp.where(
            over, 0.0, (1.0 - jnp.abs(cpu_frac - mem_frac)) * 100.0
        )
        least = (
            jnp.clip(1.0 - cpu_frac, 0.0, 1.0)
            + jnp.clip(1.0 - mem_frac, 0.0, 1.0)
        ) * 50.0
        active_soft = pod_sc & ~hard
        soft_counts = jnp.sum(
            jnp.where(active_soft[:, None, None], counts, 0), axis=0
        ).astype(jnp.float32)
        spread_score = jnp.where(
            jnp.any(active_soft), 100.0 / (1.0 + soft_counts), 0.0
        )
        pref_score = jnp.sum(
            pref_w[:, None, None] * tcounts.astype(jnp.float32), axis=0
        )
        score = (
            params.balanced_weight * balanced
            + params.least_weight * least
            + params.spread_weight * spread_score
            + params.affinity_weight * pref_score
            + params.static_weight * static_f32s[profile]
        )
        score = jnp.where(feasible, score, NEG_INF)

        mx = jnp.max(score)
        found = mx > NEG_INF / 2
        cand = jnp.where(feasible & (score >= mx), flat_idx, BIG_I32)
        chosen = jnp.min(cand)
        valid = found & pod_valid
        assignment = jnp.where(found, chosen, -1)

        onehot = (flat_idx == chosen) & valid
        inc = onehot.astype(jnp.int32)
        valid_i = valid.astype(jnp.int32)
        sc_code_j = jnp.sum(
            jnp.where(onehot[None], sc_codes, 0), axis=(1, 2)
        )
        t_code_j = jnp.sum(
            jnp.where(onehot[None], term_codes, 0), axis=(1, 2)
        )
        sc_inc = (sc_codes == sc_code_j[:, None, None]).astype(jnp.int32) \
            * (sc_match.astype(jnp.int32) * valid_i)[:, None, None]
        t_same = (term_codes == t_code_j[:, None, None]).astype(jnp.int32)
        t_inc = t_same * (match_by.astype(jnp.int32) * valid_i)[:, None, None]
        o_inc = t_same * (own_anti.astype(jnp.int32) * valid_i)[:, None, None]

        new_requested = requested + inc[None] * req[:, None, None]
        pieces = [
            new_requested,
            nz + inc[None] * row[c_nonzero:c_nonzero + 2][:, None, None],
            (state[do["pod_count"]] + inc)[None],
            counts + sc_inc,
            tcounts + t_inc,
            towners + o_inc,
        ]
        if sv:
            # consume the attach slot only where it wasn't already
            # attached, and mark the volume attached on the chosen node
            sv_add = inc * sv_demand
            pieces[0] = new_requested.at[sv_col].add(sv_add)
            shared_i = jnp.where(sv_is_shared, 1, 0)
            pieces.append(sv_planes.at[slot_c].max(inc * shared_i))
        pieces.append(state[do["totals"]][None])
        new_state = jnp.concatenate(pieces)
        new_totals = totals + (
            match_by.astype(jnp.int32) * valid_i * (t_code_j < v)
        )
        return (new_state, new_totals), assignment

    totals0 = planes[do["totals"]].reshape(-1)[:t]
    (final_planes, final_totals), assignments = jax.lax.scan(
        step, (planes, totals0), (pod_ints, pod_floats)
    )
    # totals back into their plane (row 0, lane t) for the carry contract
    flat = jnp.zeros(nb * lanes, dtype=jnp.int32).at[:t].set(final_totals)
    final_planes = final_planes.at[do["totals"]].set(
        flat.reshape(nb, lanes)
    )
    return final_planes, assignments


# ----------------------------------------------------------------------
# Sparse term-slot variant: a pod references only the handful of terms
# its own (anti-)affinity names or is matched by (config-4-style
# workloads: 1 term per pod out of 100+ tracked). The dense scan does
# O(T·N) vector work per pod regardless; this variant carries the SAME
# [T]-plane state but gathers just the K referenced planes per pod and
# scatter-adds the commit back, so per-pod cost is O(K·N). The pod
# stream also shrinks from [B, 3T] term columns to [B, 4K] slots —
# ~20x less host->device upload at T≈100 over the TPU tunnel.

SPARSE_K = 8          # max term references per pod on the sparse path
SPARSE_MIN_T = 12     # below this the dense scan is already fine


def pack_sparse_slots(ints: np.ndarray, floats: np.ndarray, r: int,
                      sc: int, t: int):
    """Derive per-pod term slots from the packed dense pod stream.
    Returns (base_ints, slot_idx, slot_flags, slot_w) — or None when any
    pod references more than SPARSE_K terms (caller stays dense).
    slot_flags packs (matched, own_aff, own_anti) as bits 0/1/2."""
    c_match_by = r + 4 + 2 * sc
    mb = ints[:, c_match_by:c_match_by + t] != 0
    oa = ints[:, c_match_by + t:c_match_by + 2 * t] != 0
    oan = ints[:, c_match_by + 2 * t:c_match_by + 3 * t] != 0
    w = floats[:, :t]
    ref = mb | oa | oan | (w != 0.0)
    nref = ref.sum(axis=1)
    if nref.max(initial=0) > SPARSE_K:
        return None
    # stable argsort puts referenced term indices first, in term order
    order = np.argsort(~ref, axis=1, kind="stable")[:, :SPARSE_K]
    active = np.take_along_axis(ref, order, axis=1)
    slot_idx = np.where(active, order, 0).astype(np.int32)
    flags = (
        np.take_along_axis(mb, order, axis=1).astype(np.int32)
        | (np.take_along_axis(oa, order, axis=1).astype(np.int32) << 1)
        | (np.take_along_axis(oan, order, axis=1).astype(np.int32) << 2)
    )
    flags = np.where(active, flags, 0)
    slot_w = np.where(
        active, np.take_along_axis(w, order, axis=1), 0.0
    ).astype(np.float32)
    base = np.ascontiguousarray(ints[:, :c_match_by])
    return base, slot_idx, flags, slot_w


@functools.partial(
    jax.jit, static_argnames=("params", "r", "sc", "t", "u", "v")
)
def _xla_planes_solve_sparse(params: SolverParams, r: int, sc: int, t: int,
                             u: int, v: int, sc_meta, static_ints,
                             static_f32s, planes, base_ints, slot_idx,
                             slot_flags, slot_w):
    so, _ = _static_planes(r, sc, t, u)
    do, cd = _state_planes(r, sc, t)
    nb, lanes = planes.shape[1], planes.shape[2]

    node_valid = static_ints[so["node_valid"]] > 0
    alloc = static_ints[so["alloc"]:so["alloc"] + r]
    max_pods = static_ints[so["max_pods"]]
    masks = static_ints[so["masks"]:so["masks"] + u]
    sc_codes = static_ints[so["sc_codes"]:so["sc_codes"] + sc]
    dom_all = static_ints[so["sc_domain"]:so["sc_domain"] + u * sc].reshape(
        u, sc, nb, lanes
    )
    term_codes = static_ints[so["term_codes"]:so["term_codes"] + t]
    sc_missing = sc_codes >= v
    flat_idx = (
        jax.lax.broadcasted_iota(jnp.int32, (nb, lanes), 0) * lanes
        + jax.lax.broadcasted_iota(jnp.int32, (nb, lanes), 1)
    )
    max_skew = sc_meta[0]
    hard = sc_meta[1] > 0

    c_req, c_nonzero, c_profile, c_valid = 0, r, r + 2, r + 3
    c_pod_sc, c_sc_match = r + 4, r + 4 + sc

    def step(carry, pod):
        tcounts_all, towners_all, totals, rest = carry
        row, idxs, flags, pref_w = pod
        pod_valid = row[c_valid] > 0
        profile = row[c_profile]
        req = row[c_req:c_req + r]
        pod_sc = row[c_pod_sc:c_pod_sc + sc] > 0
        sc_match = row[c_sc_match:c_sc_match + sc] > 0
        matched = (flags & 1) > 0            # [K]
        own_aff = (flags & 2) > 0
        own_anti = (flags & 4) > 0

        requested = rest[do["requested"]:do["requested"] + r]
        fit = jnp.all(requested + req[:, None, None] <= alloc, axis=0)
        fit &= rest[do["pod_count"]] < max_pods
        static_ok = masks[profile] > 0

        counts = rest[do["sc_counts"]:do["sc_counts"] + sc]
        dom = dom_all[profile] > 0
        min_c = jnp.min(jnp.where(dom, counts, BIG_I32), axis=(1, 2))
        min_c = jnp.where(jnp.any(dom, axis=(1, 2)), min_c, 0)
        skew = counts + sc_match[:, None, None] - min_c[:, None, None]
        active_hard = pod_sc & hard
        spread_violation = jnp.any(
            active_hard[:, None, None]
            & ((skew > max_skew[:, None, None]) | sc_missing),
            axis=0,
        )

        # gather the K referenced term planes (clip-mode gathers are
        # harmless: inactive slots carry zero flags/weights)
        tc_k = jnp.take(tcounts_all, idxs, axis=0)          # [K, NB, L]
        to_k = jnp.take(towners_all, idxs, axis=0)
        codes_k = jnp.take(term_codes, idxs, axis=0)
        tmiss_k = codes_k >= v
        totals_k = jnp.take(totals, idxs)

        existing_anti = jnp.any(matched[:, None, None] & (to_k > 0), axis=0)
        own_anti_block = jnp.any(
            own_anti[:, None, None] & (tc_k > 0), axis=0
        )
        aff_here = (tc_k > 0) & ~tmiss_k
        aff_sat = jnp.all(~own_aff[:, None, None] | aff_here, axis=0)
        no_any = jnp.all(~own_aff | (totals_k == 0))
        self_all = jnp.all(~own_aff | matched)
        has_aff = jnp.any(own_aff)
        aff_ok = ~has_aff | aff_sat | (no_any & self_all)

        feasible = (
            node_valid & static_ok & fit & ~spread_violation
            & ~existing_anti & ~own_anti_block & aff_ok & pod_valid
        )

        alloc_cpu = jnp.maximum(alloc[0], 1).astype(jnp.float32)
        alloc_mem = jnp.maximum(alloc[1], 1).astype(jnp.float32)
        nz = rest[do["nonzero"]:do["nonzero"] + 2]
        cpu_frac = (nz[0] + row[c_nonzero]).astype(jnp.float32) / alloc_cpu
        mem_frac = (nz[1] + row[c_nonzero + 1]).astype(
            jnp.float32
        ) / alloc_mem
        over = (cpu_frac >= 1.0) | (mem_frac >= 1.0)
        balanced = jnp.where(
            over, 0.0, (1.0 - jnp.abs(cpu_frac - mem_frac)) * 100.0
        )
        least = (
            jnp.clip(1.0 - cpu_frac, 0.0, 1.0)
            + jnp.clip(1.0 - mem_frac, 0.0, 1.0)
        ) * 50.0
        active_soft = pod_sc & ~hard
        soft_counts = jnp.sum(
            jnp.where(active_soft[:, None, None], counts, 0), axis=0
        ).astype(jnp.float32)
        spread_score = jnp.where(
            jnp.any(active_soft), 100.0 / (1.0 + soft_counts), 0.0
        )
        pref_score = jnp.sum(
            pref_w[:, None, None] * tc_k.astype(jnp.float32), axis=0
        )
        score = (
            params.balanced_weight * balanced
            + params.least_weight * least
            + params.spread_weight * spread_score
            + params.affinity_weight * pref_score
            + params.static_weight * static_f32s[profile]
        )
        score = jnp.where(feasible, score, NEG_INF)

        mx = jnp.max(score)
        found = mx > NEG_INF / 2
        cand = jnp.where(feasible & (score >= mx), flat_idx, BIG_I32)
        chosen = jnp.min(cand)
        valid = found & pod_valid
        assignment = jnp.where(found, chosen, -1)

        onehot = (flat_idx == chosen) & valid
        inc = onehot.astype(jnp.int32)
        valid_i = valid.astype(jnp.int32)
        sc_code_j = jnp.sum(
            jnp.where(onehot[None], sc_codes, 0), axis=(1, 2)
        )
        sc_inc = (sc_codes == sc_code_j[:, None, None]).astype(jnp.int32) \
            * (sc_match.astype(jnp.int32) * valid_i)[:, None, None]

        # per-slot commit, scatter-added back into the [T] planes
        t_code_j = jnp.sum(
            jnp.where(onehot[None], codes_k, 0), axis=(1, 2)
        )                                                     # [K]
        t_same = (codes_k == t_code_j[:, None, None]).astype(jnp.int32)
        m_i = matched.astype(jnp.int32) * valid_i
        a_i = own_anti.astype(jnp.int32) * valid_i
        new_tcounts = tcounts_all.at[idxs].add(
            t_same * m_i[:, None, None]
        )
        new_towners = towners_all.at[idxs].add(
            t_same * a_i[:, None, None]
        )
        new_totals = totals.at[idxs].add(m_i * (t_code_j < v))

        new_rest = jnp.concatenate([
            requested + inc[None] * req[:, None, None],
            nz + inc[None] * row[c_nonzero:c_nonzero + 2][:, None, None],
            (rest[do["pod_count"]] + inc)[None],
            counts + sc_inc,
        ])
        return (new_tcounts, new_towners, new_totals, new_rest), assignment

    # split the carry so the hot [T] planes scatter in place
    tcounts0 = planes[do["term_counts"]:do["term_counts"] + t]
    towners0 = planes[do["term_owners"]:do["term_owners"] + t]
    totals0 = planes[do["totals"]].reshape(-1)[:t]
    rest0 = planes[:do["term_counts"]]
    (tcounts_f, towners_f, totals_f, rest_f), assignments = jax.lax.scan(
        step, (tcounts0, towners0, totals0, rest0),
        (base_ints, slot_idx, slot_flags, slot_w),
    )
    flat = jnp.zeros(nb * lanes, dtype=jnp.int32).at[:t].set(totals_f)
    final_planes = jnp.concatenate([
        rest_f, tcounts_f, towners_f, flat.reshape(1, nb, lanes)
    ])
    return final_planes, assignments


def _scatter_flat_add(planes, rows, cols, vals):
    """Donated scatter-add into [C, NB, 128] planes; ``cols`` are flat
    node indices (the [C, N] view — the reshape is row-major, so flat
    col == node index)."""
    c, nb, lanes = planes.shape
    flat = planes.reshape(c, nb * lanes)
    return flat.at[rows, cols].add(vals).reshape(c, nb, lanes)


def _scatter_flat_set(planes, rows, cols, vals):
    c, nb, lanes = planes.shape
    flat = planes.reshape(c, nb * lanes)
    return flat.at[rows, cols].set(vals).reshape(c, nb, lanes)


# device-resident mirror update kernels (ops.mirror): the plane stack
# is donated so the update happens in place on device and only the
# index/value triples cross the link
_scatter_flat_add_jit = jax.jit(_scatter_flat_add, donate_argnums=(0,))
_scatter_flat_set_jit = jax.jit(_scatter_flat_set, donate_argnums=(0,))


class _PlanesScatterHooks:
    """Mirror scatter hooks shared by the device planes backends
    (XlaPlanes + Pallas — both carry PState/PStatic device arrays)."""

    def scatter_state_add(self, pstate, rows, cols, vals):
        planes = _scatter_flat_add_jit(pstate.planes, rows, cols, vals)
        return (PState(planes=planes),
                int(rows.nbytes + cols.nbytes + vals.nbytes))

    def scatter_static_set(self, pstatic, rows, cols, vals):
        ints = _scatter_flat_set_jit(pstatic.ints, rows, cols, vals)
        return (pstatic._replace(ints=ints),
                int(rows.nbytes + cols.nbytes + vals.nbytes))


class XlaPlanesBackend(_PlanesScatterHooks):
    """Gather-free scan backend on the planes layout — the fallback for
    constraint spaces too wide for the unrolled pallas kernel. Wide term
    axes (T ≥ SPARSE_MIN_T) with few per-pod references ride the sparse
    term-slot scan: O(K·N) per pod instead of O(T·N)."""

    name = "xla-planes"

    def prepare(self, cluster, batch):
        return prepare(cluster, batch)

    def prepare_state_only(self, cluster, batch):
        return prepare_state(cluster, batch)

    def solve_lazy(self, params, pstatic, pstate, pod_ints, pod_floats):
        """Dispatch the solve; the returned assignments handle is a
        device array the caller materializes later (jax dispatch is
        async, so host work can overlap the device solve)."""
        t = pstatic.t
        if t >= SPARSE_MIN_T and pstatic.sv == 0:
            # the sparse term-slot variant predates the sv planes; sv
            # epochs take the dense scan (wide-term + shared-volume
            # workloads are not a measured combination)
            sparse = pack_sparse_slots(
                np.asarray(pod_ints), np.asarray(pod_floats),
                pstatic.r, pstatic.sc, t,
            )
            if sparse is not None:
                base, slot_idx, slot_flags, slot_w = sparse
                new_planes, assignments = _xla_planes_solve_sparse(
                    params, pstatic.r, pstatic.sc, t, pstatic.u,
                    pstatic.v, pstatic.sc_meta, pstatic.ints,
                    pstatic.f32s, pstate.planes, jnp.asarray(base),
                    jnp.asarray(slot_idx), jnp.asarray(slot_flags),
                    jnp.asarray(slot_w),
                )
                return assignments, PState(planes=new_planes)
        new_planes, assignments = _xla_planes_solve(
            params, pstatic.r, pstatic.sc, pstatic.t, pstatic.u,
            pstatic.v, pstatic.sc_meta, pstatic.ints, pstatic.f32s,
            pstate.planes, jnp.asarray(pod_ints), jnp.asarray(pod_floats),
            sv=pstatic.sv,
        )
        return assignments, PState(planes=new_planes)

    @staticmethod
    def materialize(handle):
        return np.asarray(handle)

    def solve(self, params, pstatic, pstate, pod_ints, pod_floats):
        h, state = self.solve_lazy(params, pstatic, pstate, pod_ints,
                                   pod_floats)
        return self.materialize(h), state


class PallasBackend(_PlanesScatterHooks):
    """Drop-in solve backend for SolverSession (see session.py)."""

    name = "pallas"

    def __init__(self, interpret: bool = False):
        self.interpret = interpret

    def prepare(self, cluster, batch):
        if cluster.sv_attached is not None:
            # the unrolled kernel has no sv planes; the chain falls to
            # the planes scan for shared-volume epochs
            raise ValueError(
                "pallas kernel does not carry shared-volume planes")
        return prepare(cluster, batch)

    def prepare_state_only(self, cluster, batch):
        return prepare_state(cluster, batch)

    def solve_lazy(self, params, pstatic, pstate, pod_ints, pod_floats):
        """Async-dispatched solve; materialize the handle later."""
        assignments, new_state = _run(
            params, pstatic, pstate,
            jnp.asarray(pod_ints), jnp.asarray(pod_floats),
            self.interpret,
        )
        return assignments, new_state

    @staticmethod
    def materialize(handle):
        return np.asarray(handle)[:, 0]

    def solve(self, params, pstatic, pstate, pod_ints, pod_floats):
        h, state = self.solve_lazy(params, pstatic, pstate, pod_ints,
                                   pod_floats)
        return self.materialize(h), state
