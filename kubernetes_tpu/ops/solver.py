"""Device assignment solvers.

``solve_scan`` is the serial-equivalent batch solver: one ``lax.scan`` step
per pod (in queue priority order), each step evaluating EVERY node with
dense vector ops — feasibility (capacity fit, pod-count cap, topology-skew,
(anti-)affinity domain counts, static predicate masks) and scores
(balanced/least allocation, spread, preferred affinity, static) — then
committing the argmax and updating capacity/count state with one-hot adds.

This replaces the reference's hot path 1:1: a scan step IS one
``scheduleOne`` cycle (SURVEY.md section 3.2), except the per-node work the
reference fans out over 16 goroutines with adaptive sampling
(``generic_scheduler.go:179-199``) runs as full-width vector ops — all
nodes, no sampling. Intra-batch interactions (pod A consuming capacity,
shifting topology counts for pod B) are exact by construction, which is the
"hard part (2)" called out in SURVEY.md section 7.

Everything is static-shaped (pods and nodes padded to buckets), int32/f32,
with no data-dependent Python control flow — one XLA compilation per
(bucket-shape) signature, reused across batches.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.ops.encode import EncodedBatch, EncodedCluster

NEG_INF = -1e30
BIG = np.int32(2**30)


@dataclass(frozen=True)
class SolverParams:
    """Score weights mirroring the default provider's plugin weights
    (provider.py): balanced 1, least-allocated 1, topology-spread 2,
    inter-pod affinity 1. Spread/affinity device scores are rank-equivalent
    to the host's min-max normalized forms (monotone in the same counts)."""

    balanced_weight: float = 1.0
    least_weight: float = 1.0
    spread_weight: float = 2.0
    affinity_weight: float = 1.0
    static_weight: float = 1.0


class _State(NamedTuple):
    requested: jnp.ndarray          # [N, R] int32
    nonzero_requested: jnp.ndarray  # [N, 2] int32
    pod_count: jnp.ndarray          # [N] int32
    sc_counts: jnp.ndarray          # [SC, V+1] int32
    term_counts: jnp.ndarray        # [T, V+1] int32
    term_owners: jnp.ndarray        # [T, V+1] int32


class _PodIn(NamedTuple):
    request: jnp.ndarray        # [R]
    nonzero_request: jnp.ndarray  # [2]
    profile: jnp.ndarray        # scalar int32
    valid: jnp.ndarray          # scalar bool (real & expressible)
    pod_sc: jnp.ndarray         # [SC] bool
    pod_sc_match: jnp.ndarray   # [SC] bool
    match_by: jnp.ndarray       # [T] bool
    own_aff: jnp.ndarray        # [T] bool
    own_anti: jnp.ndarray       # [T] bool
    pref_weight: jnp.ndarray    # [T] f32


class _Static(NamedTuple):
    allocatable: jnp.ndarray     # [N, R]
    max_pods: jnp.ndarray        # [N]
    static_masks: jnp.ndarray    # [U, N] bool
    static_scores: jnp.ndarray   # [U, N] f32
    sc_codes: jnp.ndarray        # [SC, N] int32 (V = missing)
    sc_max_skew: jnp.ndarray     # [SC]
    sc_hard: jnp.ndarray         # [SC] bool
    sc_domain: jnp.ndarray       # [U, SC, V+1] bool
    term_codes: jnp.ndarray      # [T, N] int32
    node_valid: jnp.ndarray      # [N] bool


def _step(static: _Static, params: SolverParams, state: _State, pod: _PodIn):
    n = static.allocatable.shape[0]
    v = state.sc_counts.shape[1] - 1

    # ---- feasibility --------------------------------------------------
    fit = jnp.all(
        state.requested + pod.request[None, :] <= static.allocatable, axis=1
    )
    fit &= state.pod_count < static.max_pods
    static_ok = static.static_masks[pod.profile]

    # topology spread (hard constraints)
    counts_at = jnp.take_along_axis(state.sc_counts, static.sc_codes, axis=1)  # [SC, N]
    domain = static.sc_domain[pod.profile]                                     # [SC, V+1]
    min_c = jnp.min(
        jnp.where(domain[:, :v], state.sc_counts[:, :v], BIG), axis=1
    )
    min_c = jnp.where(jnp.any(domain[:, :v], axis=1), min_c, 0)
    skew = counts_at + pod.pod_sc_match[:, None].astype(jnp.int32) - min_c[:, None]
    missing = static.sc_codes >= v
    active_hard = pod.pod_sc & static.sc_hard
    spread_violation = jnp.any(
        active_hard[:, None] & ((skew > static.sc_max_skew[:, None]) | missing),
        axis=0,
    )

    # inter-pod affinity
    tcounts_at = jnp.take_along_axis(state.term_counts, static.term_codes, axis=1)  # [T, N]
    towners_at = jnp.take_along_axis(state.term_owners, static.term_codes, axis=1)
    t_missing = static.term_codes >= v
    existing_anti_block = jnp.any(
        pod.match_by[:, None] & (towners_at > 0), axis=0
    )
    own_anti_block = jnp.any(pod.own_anti[:, None] & (tcounts_at > 0), axis=0)
    aff_here = (tcounts_at > 0) & ~t_missing
    aff_sat = jnp.all(~pod.own_aff[:, None] | aff_here, axis=0)
    # first-pod-of-group special case (filtering.go): no matches anywhere
    # for ANY of its terms and the pod matches its own terms
    totals = jnp.sum(state.term_counts[:, :v], axis=1)
    no_any = jnp.all(~pod.own_aff | (totals == 0))
    self_all = jnp.all(~pod.own_aff | pod.match_by)
    has_aff = jnp.any(pod.own_aff)
    aff_ok = jnp.where(has_aff, aff_sat | (no_any & self_all), True)

    feasible = (
        static.node_valid
        & static_ok
        & fit
        & ~spread_violation
        & ~existing_anti_block
        & ~own_anti_block
        & aff_ok
        & pod.valid
    )

    # ---- scores -------------------------------------------------------
    alloc_cpu = jnp.maximum(static.allocatable[:, 0], 1).astype(jnp.float32)
    alloc_mem = jnp.maximum(static.allocatable[:, 1], 1).astype(jnp.float32)
    cpu_frac = (
        state.nonzero_requested[:, 0] + pod.nonzero_request[0]
    ).astype(jnp.float32) / alloc_cpu
    mem_frac = (
        state.nonzero_requested[:, 1] + pod.nonzero_request[1]
    ).astype(jnp.float32) / alloc_mem
    over = (cpu_frac >= 1.0) | (mem_frac >= 1.0)
    balanced = jnp.where(over, 0.0, (1.0 - jnp.abs(cpu_frac - mem_frac)) * 100.0)
    least = (
        jnp.clip(1.0 - cpu_frac, 0.0, 1.0) + jnp.clip(1.0 - mem_frac, 0.0, 1.0)
    ) * 50.0

    active_soft = pod.pod_sc & ~static.sc_hard
    soft_counts = jnp.sum(
        jnp.where(active_soft[:, None], counts_at, 0), axis=0
    ).astype(jnp.float32)
    spread_score = 100.0 / (1.0 + soft_counts)
    has_soft = jnp.any(active_soft)
    spread_score = jnp.where(has_soft, spread_score, 0.0)

    pref_score = jnp.sum(
        pod.pref_weight[:, None] * tcounts_at.astype(jnp.float32), axis=0
    )

    score = (
        params.balanced_weight * balanced
        + params.least_weight * least
        + params.spread_weight * spread_score
        + params.affinity_weight * pref_score
        + params.static_weight * static.static_scores[pod.profile]
    )
    score = jnp.where(feasible, score, NEG_INF)

    best = jnp.argmax(score)
    found = jnp.any(feasible)
    chosen = jnp.where(found, best, -1)
    valid = found & pod.valid

    # ---- commit (one-hot updates) ------------------------------------
    onehot = (jnp.arange(n) == chosen) & valid
    inc = onehot.astype(jnp.int32)
    new_state = _State(
        requested=state.requested + inc[:, None] * pod.request[None, :],
        nonzero_requested=state.nonzero_requested
        + inc[:, None] * pod.nonzero_request[None, :],
        pod_count=state.pod_count + inc,
        sc_counts=state.sc_counts.at[
            jnp.arange(state.sc_counts.shape[0]),
            static.sc_codes[:, jnp.maximum(chosen, 0)],
        ].add((pod.pod_sc_match & valid).astype(jnp.int32)),
        term_counts=state.term_counts.at[
            jnp.arange(state.term_counts.shape[0]),
            static.term_codes[:, jnp.maximum(chosen, 0)],
        ].add((pod.match_by & valid).astype(jnp.int32)),
        term_owners=state.term_owners.at[
            jnp.arange(state.term_owners.shape[0]),
            static.term_codes[:, jnp.maximum(chosen, 0)],
        ].add((pod.own_anti & valid).astype(jnp.int32)),
    )
    return new_state, chosen


def build_static(cluster: EncodedCluster, batch: EncodedBatch,
                 device: bool = False) -> _Static:
    """Assemble the solve-invariant arrays (static across batches of one
    session). With ``device=True`` they are committed to the default
    device immediately so later jit calls skip the host→device transfer."""
    n = cluster.allocatable.shape[0]
    v = batch.num_values
    sc_codes = np.minimum(
        cluster.topo_codes[:, batch.sc_key_idx].T, v
    ).astype(np.int32)
    term_codes = np.minimum(
        cluster.topo_codes[:, batch.term_key_idx].T, v
    ).astype(np.int32)
    node_valid = np.zeros(n, dtype=bool)
    node_valid[: cluster.num_real_nodes] = True
    static = _Static(
        allocatable=cluster.allocatable,
        max_pods=cluster.max_pods,
        static_masks=batch.static_masks,
        static_scores=batch.static_scores,
        sc_codes=sc_codes,
        sc_max_skew=batch.sc_max_skew,
        sc_hard=batch.sc_hard,
        sc_domain=batch.sc_domain,
        term_codes=term_codes,
        node_valid=node_valid,
    )
    # one batched transfer (see pack_podin on per-call latency)
    return jax.device_put(static) if device else \
        jax.tree.map(jnp.asarray, static)


def build_state(cluster: EncodedCluster, batch: EncodedBatch,
                device: bool = False) -> _State:
    state = _State(
        requested=cluster.requested,
        nonzero_requested=cluster.nonzero_requested,
        pod_count=cluster.pod_count,
        sc_counts=batch.sc_counts,
        term_counts=batch.term_counts,
        term_owners=batch.term_owners,
    )
    return jax.device_put(state) if device else \
        jax.tree.map(jnp.asarray, state)


def pack_podin(batch) -> Tuple[np.ndarray, np.ndarray]:
    """Pack the pod stream into TWO host arrays (one int32, one f32).
    Every device buffer upload pays the full host↔device round-trip
    latency (~tens of ms over a TPU tunnel), so shipping ten small
    arrays costs more than the solve — two packed buffers amortize it.
    Unpacked on device by ``_unpack_podin`` (slicing fuses for free).
    Timed by the CALLER (SolverSession observes the ``pack`` phase):
    warming solves must stay out of the measured series, and only the
    session knows whether a solve is warming."""
    b = batch.requests.shape[0]
    valid = np.zeros(b, dtype=bool)
    valid[: batch.num_real_pods] = True
    valid &= ~batch.inexpressible
    cols = [
        batch.requests,
        batch.nonzero_requests,
        batch.profile_idx.reshape(b, 1),
        valid.reshape(b, 1).astype(np.int32),
        batch.pod_sc.astype(np.int32),
        batch.pod_sc_match.astype(np.int32),
        batch.match_by.astype(np.int32),
        batch.own_aff.astype(np.int32),
        batch.own_anti.astype(np.int32),
    ]
    pod_sv = getattr(batch, "pod_sv", None)
    if pod_sv is not None:
        # shared-volume epochs append (slot, attach column) — absent
        # otherwise, so non-sv workloads keep their compiled shapes
        cols.append(pod_sv)
    ints = np.concatenate(cols, axis=1, dtype=np.int32)
    return ints, np.asarray(batch.pref_weight, dtype=np.float32)


def place_podin(ints: np.ndarray, floats: np.ndarray, sharding=None):
    """Commit the packed pod stream to device. With ``sharding`` (the
    mesh tier passes its replicated NamedSharding) the two buffers are
    PLACED in one step, so the jitted shard_map solve reads them where
    they landed instead of resharding from the default device at every
    dispatch — the pod-stream half of the NamedSharding-placed-uploads
    contract (the plane half lives in ``parallel/sharded.py``)."""
    if sharding is None:
        return jnp.asarray(ints), jnp.asarray(floats)
    import jax as _jax

    return (_jax.device_put(np.asarray(ints), sharding),
            _jax.device_put(np.asarray(floats), sharding))


def _unpack_podin(ints: jnp.ndarray, floats: jnp.ndarray,
                  r: int, sc: int, t: int) -> _PodIn:
    """Device-side inverse of ``pack_podin`` (column widths are static,
    derived from the static arrays' shapes)."""
    # slice clamping would silently misalign fields on a width mismatch;
    # keep the loud failure the per-array path used to give
    if ints.shape[1] != r + 4 + 2 * sc + 3 * t:
        raise ValueError(
            f"packed pod stream width {ints.shape[1]} does not match the "
            f"static constraint space (r={r}, sc={sc}, t={t})"
        )
    o = 0
    request = ints[:, o:o + r]; o += r
    nonzero = ints[:, o:o + 2]; o += 2
    profile = ints[:, o]; o += 1
    valid = ints[:, o] != 0; o += 1
    pod_sc = ints[:, o:o + sc] != 0; o += sc
    pod_sc_match = ints[:, o:o + sc] != 0; o += sc
    match_by = ints[:, o:o + t] != 0; o += t
    own_aff = ints[:, o:o + t] != 0; o += t
    own_anti = ints[:, o:o + t] != 0; o += t
    return _PodIn(
        request=request,
        nonzero_request=nonzero,
        profile=profile,
        valid=valid,
        pod_sc=pod_sc,
        pod_sc_match=pod_sc_match,
        match_by=match_by,
        own_aff=own_aff,
        own_anti=own_anti,
        pref_weight=floats,
    )


@partial(jax.jit, static_argnames=("params",))
def _solve_packed(static: _Static, state: _State, pod_ints, pod_floats,
                  params: SolverParams):
    pods = _unpack_podin(
        pod_ints, pod_floats,
        static.allocatable.shape[1],
        static.sc_codes.shape[0],
        static.term_codes.shape[0],
    )
    final_state, assignments = jax.lax.scan(
        partial(_step, static, params), state, pods
    )
    return final_state, assignments


def solve_scan(
    cluster: EncodedCluster, batch: EncodedBatch,
    params: SolverParams = SolverParams(),
):
    """Run the scan solver. Returns (assignments [B] int32 node indices,
    -1 = unschedulable/fallback)."""
    static = build_static(cluster, batch)
    state = build_state(cluster, batch)
    ints, floats = pack_podin(batch)
    _, assignments = _solve_packed(static, state, ints, floats, params)
    return np.asarray(assignments)


# ---------------------------------------------------------------------------
# what-if solves (the cluster autoscaler's virtual-column hook)

# keeps a column feasible but strictly below every real-node score: the
# scan only spills onto a penalized column when NO unpenalized node is
# feasible — exactly the "would a new node help" question. Real scores
# are O(hundreds) (balanced/least ≤ 200, spread ≤ 100, static small),
# so one tier of 1e6 cleanly separates real < upcoming < virtual.
VIRTUAL_NODE_PENALTY = np.float32(1.0e6)


def solve_whatif(
    cluster: EncodedCluster, batch: EncodedBatch,
    params: SolverParams = SolverParams(),
    deprioritized_cols=(),
    disabled_cols=(),
):
    """Scan solve with per-column overrides, for autoscaler what-ifs:

    - ``deprioritized_cols``: node columns (e.g. the K appended virtual
      template nodes, or still-booting "upcoming" nodes) whose static
      score is pushed down by ``VIRTUAL_NODE_PENALTY`` — a mapping
      ``{col: penalty}`` applies per-column tiers (upcoming nodes get a
      smaller penalty than hypothetical ones, so pods prefer capacity
      that is already paid for);
    - ``disabled_cols``: node columns removed from the solve entirely
      (the scale-down "do its pods fit elsewhere" question).

    Returns ``(assignments [num_real_pods], per-node assigned counts
    [N])``. The batch-wide scan IS the estimator: one solve answers the
    question for every pending pod at once, replacing the reference
    cluster-autoscaler's one-pod-at-a-time scheduler simulation.
    """
    static = build_static(cluster, batch)
    n = cluster.allocatable.shape[0]
    if len(deprioritized_cols):
        scores = np.array(batch.static_scores, dtype=np.float32, copy=True)
        if hasattr(deprioritized_cols, "items"):
            for col, penalty in deprioritized_cols.items():
                scores[:, int(col)] -= np.float32(penalty)
        else:
            cols = np.asarray(list(deprioritized_cols), dtype=np.int64)
            scores[:, cols] -= VIRTUAL_NODE_PENALTY
        static = static._replace(static_scores=jnp.asarray(scores))
    if len(disabled_cols):
        node_valid = np.zeros(n, dtype=bool)
        node_valid[: cluster.num_real_nodes] = True
        node_valid[np.asarray(list(disabled_cols), dtype=np.int64)] = False
        static = static._replace(node_valid=jnp.asarray(node_valid))
    state = build_state(cluster, batch)
    ints, floats = pack_podin(batch)
    _, assignments = _solve_packed(static, state, ints, floats, params)
    a = np.asarray(assignments)[: batch.num_real_pods]
    counts = np.bincount(a[a >= 0], minlength=n)
    return a, counts
