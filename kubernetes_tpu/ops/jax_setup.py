"""Process-wide JAX configuration for the solver path.

Enables the persistent compilation cache so a fresh process (every
benchmark run; every scheduler restart) reuses XLA binaries instead of
re-paying the ~10s device compile for each solver shape. Must be imported
before the first jit compilation — ``kubernetes_tpu.ops`` imports it
first. Override the location with ``KTPU_JAX_CACHE_DIR`` (empty string
disables).
"""

from __future__ import annotations

import os

_DEFAULT_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), ".jax_cache")


def configure() -> None:
    cache_dir = os.environ.get("KTPU_JAX_CACHE_DIR", _DEFAULT_DIR)
    if not cache_dir:
        return
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass  # cache is an optimization; never fail import over it


configure()
