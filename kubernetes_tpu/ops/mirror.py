"""Device-resident cluster mirror: scatter watch deltas into the planes.

PR 10 made solver state live on device (donated buffers) and PR 12 made
encode a delta pass, but every solve cycle still paid a host-side plane
build plus h2d of node columns whenever anything beyond the sidecar's
own commits touched the cache. This module finishes the thought: the
pod×node planes become a persistent device-resident mirror of the
cluster, and watch-event deltas — pod bind/delete/update, node
capacity changes — are applied by jitted row/column scatter kernels
chained onto the donated state carry instead of re-encoding.

The contract has three parts:

- ``DeltaJournal``: the :class:`SchedulerCache` notes one compact
  ``DeltaRecord`` per ``mutation_seq`` bump (under the cache lock).
  The journal is a bounded ring; a window that is no longer
  contiguous (evicted, or a bump site that predates the journal)
  reads as a gap and forces a reseed — safety never depends on the
  journal being complete.
- ``DeviceClusterMirror.catch_up(lo, hi)``: translates the journaled
  window into exact int32 scatter entries against the RESIDENT
  encoding space (the one the last full encode retained), then
  dispatches them through the active backend's scatter hooks
  (``scatter_state_add`` / ``scatter_static_set``, donated in-place
  updates). Translation is transactional: every record is translated
  host-side first, and ANY record the space cannot express
  bit-exactly returns None — the caller falls back to the full host
  encode + re-seed, which is exactly the ``KTPU_MIRROR=off`` path.
- Expressibility is conservative and arithmetic-exact. A pod delta is
  only scattered when the result is bit-identical to a rebuild:
  the node is in the resident index, the pod matches no tracked
  spread constraint or (anti-)affinity term and owns none, it has no
  volumes while CSI attach columns exist, and its memory/ephemeral
  requests are KiB-aligned (``_kib`` is a ceiling division applied to
  SUMS at rebuild — per-pod deltas are exact only on aligned values).
  A node update scatters only when old and new differ in nothing but
  ``status.allocatable`` (labels, taints, unschedulable, images all
  equal — anything else touches static masks/scores/topology codes).

Scatter bytes are the only per-event h2d left (indices + values, a
few KiB) and are booked into ``solver_transfer_bytes_total`` as h2d
plus the separate ``scatter`` attribution ledger; they never enter
the donated ledger.
"""

from __future__ import annotations

import logging
import os
import threading
from collections import deque
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from kubernetes_tpu.ops.encode import _kib, _resource_row
from kubernetes_tpu.ops.pallas_solver import _state_planes, _static_planes
from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo

_logger = logging.getLogger(__name__)

# journal ring capacity: ~8k mutations of headroom between two solves.
# An event storm that overflows it reads as a gap → one reseed — the
# exact behavior a lost watch connection has always had.
JOURNAL_CAP = 8192


def mirror_enabled() -> bool:
    """KTPU_MIRROR kill switch — default ON; ``off``/``0``/``false``
    selects the PR 12 delta-encode path (the differential reference)."""
    return os.environ.get("KTPU_MIRROR", "on").strip().lower() not in (
        "off", "0", "false",
    )


class DeltaRecord(NamedTuple):
    """One cache mutation, journaled at its ``mutation_seq``."""

    seq: int
    kind: str
    a: object = None
    b: object = None


class DeltaJournal:
    """Bounded ring of cache mutations, written under the cache lock."""

    def __init__(self, cap: int = JOURNAL_CAP):
        self._recs: deque = deque(maxlen=cap)
        self._lock = threading.Lock()

    def note(self, seq: int, kind: str, a=None, b=None) -> None:
        with self._lock:
            self._recs.append(DeltaRecord(seq, kind, a, b))

    def window(self, lo: int, hi: int) -> Optional[List[DeltaRecord]]:
        """Records with lo < seq ≤ hi, or None when the ring no longer
        covers that range contiguously (evicted entries, or a mutation
        bumped by a site the journal does not instrument — both must
        read as 'mirror diverged', never as 'nothing happened')."""
        if hi <= lo:
            return []
        with self._lock:
            recs = [r for r in self._recs if lo < r.seq <= hi]
        if len(recs) != hi - lo or recs[0].seq != lo + 1:
            return None
        return recs


def _pad_pow2(m: int) -> int:
    """Scatter-entry padding bucket (pow2, min 8): bounds the number of
    distinct compiled scatter shapes."""
    p = 8
    while p < m:
        p *= 2
    return p


class DeviceClusterMirror:
    """Owns the catch-up path for one :class:`SolverSession`: journal
    window → exact scatter entries → donated device update."""

    def __init__(self, session, journal: DeltaJournal):
        self._session = session
        self._journal = journal
        # telemetry (the mirror[] diag segment reads these)
        self.events_applied = 0
        self.catch_ups = 0
        self.scatter_bytes_total = 0
        self.reseeds = 0   # full rebuilds AFTER the first seed
        self.seeds = 0
        self._node_map: Optional[Dict[str, int]] = None

    # ------------------------------------------------------------------
    def note_seeded(self, cold: bool, warming: bool) -> None:
        """Called by the session after every full/state-only rebuild:
        the device planes were just re-seeded from a host encode, so
        the cached node index is stale and — unless this was the cold
        start or a warm-up — the rebuild counts as a mirror reseed."""
        self._node_map = None
        if warming:
            return
        if cold:
            self.seeds += 1
        else:
            self.reseeds += 1

    def info(self) -> dict:
        return {
            "events": self.events_applied,
            "catch_ups": self.catch_ups,
            "scatter_mb": round(self.scatter_bytes_total / 1e6, 3),
            "reseeds": self.reseeds,
        }

    # ------------------------------------------------------------------
    def catch_up(self, from_seq: int, to_seq: int) -> Optional[int]:
        """Scatter the journaled (from_seq, to_seq] window into the
        resident device planes. Returns the scatter h2d bytes on
        success (0 = the window nets out to nothing), or None when the
        window is inexpressible/gapped — the caller reseeds via the
        full host encode, which is the mirror-off behavior."""
        sess = self._session
        backend = sess._active
        if (
            not hasattr(backend, "scatter_state_add")
            or sess._state is None
            or sess._static is None
            or sess._cluster is None
            or sess._encoder is None
        ):
            return None
        recs = self._journal.window(from_seq, to_seq)
        if recs is None:
            return None
        try:
            plan = self._translate(recs)
        except Exception:  # noqa: BLE001 — any doubt → full rebuild
            _logger.exception("mirror delta translation failed; reseed")
            return None
        if plan is None:
            return None
        adds, sets = plan
        try:
            nbytes = self._dispatch(backend, adds, sets)
        except Exception:  # noqa: BLE001
            # the device update may have half-applied: poison the
            # session AND drop the static fingerprint so the rebuild
            # re-uploads everything
            _logger.exception("mirror scatter dispatch failed; reseed")
            sess.invalidate()
            sess._static_fp = None
            return None
        self.catch_ups += 1
        self.events_applied += len(recs)
        self.scatter_bytes_total += nbytes
        return nbytes

    # ------------------------------------------------------------------
    def _node_index(self) -> Dict[str, int]:
        """name → flat plane column of the RESIDENT encoding (column i
        of every plane is ``cluster.node_names[i]``; the planes-layout
        [C, NB, 128] reshape is row-major, so the flat index is the
        same). Rebuilds invalidate via ``note_seeded``."""
        if self._node_map is None:
            self._node_map = {
                name: i
                for i, name in enumerate(self._session._cluster.node_names)
            }
        return self._node_map

    def _translate(
        self, recs: List[DeltaRecord],
    ) -> Optional[Tuple[list, dict]]:
        """Journal window → (state add-entries, static set-entries).
        None = some record cannot be expressed bit-exactly against the
        resident encoding space."""
        sess = self._session
        static = sess._static
        do, _ = _state_planes(static.r, static.sc, static.t, static.sv)
        so, _ = _static_planes(static.r, static.sc, static.t, static.u)
        names = sess._encoder._resource_names
        nmap = self._node_index()
        adds: list = []
        sets: dict = {}
        for rec in recs:
            k = rec.kind
            if k == "assume_bulk":
                # bulk-committed batch pods: the solve already applied
                # them to the device carry — scattering again would
                # double-count
                continue
            if k in ("assume", "pod_add"):
                ok = self._pod_delta(adds, rec.a, +1, do, names, nmap)
            elif k == "pod_del":
                ok = self._pod_delta(adds, rec.a, -1, do, names, nmap)
            elif k in ("pod_update", "pod_move"):
                ok = self._pod_delta(adds, rec.a, -1, do, names, nmap) \
                    and self._pod_delta(adds, rec.b, +1, do, names, nmap)
            elif k == "node_update":
                ok = self._node_set(sets, rec.a, rec.b, so, names, nmap)
            else:
                # "external", "node_add", "node_del", unknown kinds:
                # the node set / arbitrary host state changed
                ok = False
            if not ok:
                return None
        return adds, sets

    def _pod_delta(self, out: list, pod, sign: int, do, names,
                   nmap) -> bool:
        """Append (plane row, node col, value) add-entries for one
        pod's contribution to the dynamic planes; False = reseed."""
        node_name = getattr(pod.spec, "node_name", "") or ""
        if not node_name:
            return True   # unbound pod: no node-plane impact
        col = nmap.get(node_name)
        if col is None:
            return False
        enc = self._session._encoder
        # volumes consume CSI attach-column / shared-volume budget —
        # per-claim set semantics the additive model cannot replay
        if enc._attach_col and getattr(pod.spec, "volumes", None):
            return False
        # pods owning spread/affinity terms contribute to tracked-term
        # registration and anti-term owner counts
        if getattr(pod.spec, "topology_spread_constraints", None):
            return False
        aff = getattr(pod.spec, "affinity", None)
        if aff is not None and (
            getattr(aff, "pod_affinity", None) is not None
            or getattr(aff, "pod_anti_affinity", None) is not None
        ):
            return False
        # pods MATCHED by a tracked constraint/term land in the
        # sc_counts/term_counts value tables
        for con in (enc._constraints or []):
            if con.matches(pod):
                return False
        for term in (enc._terms or []):
            if term.matches(pod):
                return False
        pi = PodInfo.of(pod)
        req = pi.resource_request
        nz = pi.non_zero_request
        # _kib is ceil-division applied to SUMS at rebuild; per-pod
        # deltas are exact only on KiB-aligned values
        if req.memory % 1024 or req.ephemeral_storage % 1024 \
                or nz.memory % 1024:
            return False
        # scalar resources outside the tracked column set contribute
        # nothing to the planes at rebuild either — no check needed
        row_vals = _resource_row(req, names)
        for j, val in enumerate(row_vals):
            if val:
                out.append((do["requested"] + j, col, sign * val))
        if nz.milli_cpu:
            out.append((do["nonzero"], col, sign * nz.milli_cpu))
        if nz.memory:
            out.append((do["nonzero"] + 1, col, sign * _kib(nz.memory)))
        out.append((do["pod_count"], col, sign))
        return True

    def _node_set(self, sets: dict, old, new, so, names, nmap) -> bool:
        """SET-entries for a node whose old→new change is confined to
        ``status.allocatable`` (the capacity-churn fast path); anything
        touching static masks/scores/topology reseeds."""
        if old is None or new is None or old.name != new.name:
            return False
        col = nmap.get(new.name)
        if col is None:
            return False
        # attach-limit columns are derived from CSINode state per
        # driver — a capacity scatter would zero them
        if self._session._encoder._attach_col:
            return False
        if (getattr(old.metadata, "labels", None) or {}) != \
                (getattr(new.metadata, "labels", None) or {}):
            return False
        if bool(getattr(old.spec, "unschedulable", False)) != \
                bool(getattr(new.spec, "unschedulable", False)):
            return False
        if not _seq_equal(getattr(old.spec, "taints", None),
                          getattr(new.spec, "taints", None)):
            return False
        if not _seq_equal(getattr(old.status, "images", None),
                          getattr(new.status, "images", None)):
            return False
        ni = NodeInfo()
        ni.set_node(new)
        for j, val in enumerate(_resource_row(ni.allocatable, names)):
            sets[(so["alloc"] + j, col)] = val
        sets[(so["max_pods"], col)] = \
            ni.allocatable.allowed_pod_number or 1_000_000
        return True

    # ------------------------------------------------------------------
    def _dispatch(self, backend, adds: list, sets: dict) -> int:
        """Ship the translated entries through the backend's donated
        scatter hooks; returns the h2d bytes that actually crossed."""
        sess = self._session
        total = 0
        if adds:
            # combine duplicate (row, col) targets host-side (one entry
            # per target keeps the padded bucket small; .at[].add would
            # accumulate duplicates anyway)
            acc: Dict[tuple, int] = {}
            for row, col, val in adds:
                acc[(row, col)] = acc.get((row, col), 0) + val
            items = [(rc[0], rc[1], v) for rc, v in acc.items() if v]
            if items:
                rows, cols, vals = _pack_entries(items, pad_with_zero=True)
                sess._state, nb = backend.scatter_state_add(
                    sess._state, rows, cols, vals)
                total += nb
        if sets:
            # last-write-wins dedup already happened (dict); pad by
            # repeating the final entry — a duplicate same-value set is
            # deterministic
            items = [(rc[0], rc[1], v) for rc, v in sets.items()]
            rows, cols, vals = _pack_entries(items, pad_with_zero=False)
            sess._static, nb = backend.scatter_static_set(
                sess._static, rows, cols, vals)
            # the resident static no longer matches the retained
            # fingerprint; the next rebuild must not take the
            # state-only path against a stale identity
            sess._static_fp = None
            total += nb
        return total


def _seq_equal(a, b) -> bool:
    """Structural equality for api-object lists (taints, images):
    dataclass ``__eq__`` compares by value; fall back to repr so an
    identity-only type degrades to 'changed' (reseed), never 'equal'."""
    a = list(a or [])
    b = list(b or [])
    if len(a) != len(b):
        return False
    try:
        if a == b:
            return True
    except Exception:  # noqa: BLE001
        pass
    return repr(a) == repr(b)


def _pack_entries(items: list, pad_with_zero: bool):
    """(row, col, val) triples → padded int32 arrays. Add-scatters pad
    with (0, 0, 0) (adds nothing); set-scatters repeat the last real
    entry (same-value duplicate set is deterministic)."""
    m = len(items)
    pad = _pad_pow2(m)
    if pad_with_zero:
        fill = (0, 0, 0)
    else:
        fill = items[-1]
    items = items + [fill] * (pad - m)
    arr = np.asarray(items, dtype=np.int32)
    return (np.ascontiguousarray(arr[:, 0]),
            np.ascontiguousarray(arr[:, 1]),
            np.ascontiguousarray(arr[:, 2]))
