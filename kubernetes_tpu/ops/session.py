"""Device-resident solver session: the incremental host→device mirror.

The reference keeps its cluster snapshot incrementally updated with a
Generation-ordered LRU (``internal/cache/cache.go:203-287``); this module
is the device half of that idea (SURVEY.md section 7, hard part 1).
Re-encoding and re-uploading the whole cluster every batch costs more than
the solve itself (host→device over the TPU tunnel dominated the profile),
so a session:

- uploads the solve-invariant arrays (allocatable, static predicate masks,
  topology codes) to the device ONCE per cluster epoch,
- carries the dynamic state (per-node requested vectors, pod counts,
  topology/affinity count matrices) ON DEVICE between batches — the scan's
  final carry IS the next batch's initial state,
- encodes only the pod-side arrays per batch (``encode_pods_only``),
- and invalidates on ``SchedulerCache.mutation_seq`` drift: the sidecar
  accounts one expected mutation (the assume) per successfully committed
  pod; anything else that touched the cache — external pod/node events,
  serial-path binds, TTL expiry, failed binds — means the device mirror
  no longer matches the host truth and is rebuilt from a fresh snapshot.

Correctness therefore never depends on the incremental path: any doubt →
full rebuild, which is exactly the v1 behavior.
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional, Tuple

import numpy as np

from kubernetes_tpu.observability import get_tracer
from kubernetes_tpu.observability.devprof import get_devprof
from kubernetes_tpu.ops.encode import BatchEncoder, EncodedCluster
from kubernetes_tpu.ops.mirror import mirror_enabled
from kubernetes_tpu.ops.solver import (
    SolverParams,
    _solve_packed,
    build_state,
    build_static,
    pack_podin,
)

_logger = logging.getLogger(__name__)


def _tree_nbytes(tree) -> int:
    """Byte size of every array leaf in a backend's prepared static or
    state pytree — the devprof host→device transfer accounting is
    computed from the shapes/dtypes we actually ship. Only meaningful
    for backends whose ``prepare`` genuinely uploads everything it
    returns; backends that keep donated/persistent device buffers
    declare ``self_accounting`` and report their own transfer bytes
    (counting a device-resident donated plane as an upload would make
    ``solver_transfer_bytes_total`` lie — the devscale proof metric)."""
    import jax

    try:
        return sum(int(getattr(leaf, "nbytes", 0))
                   for leaf in jax.tree_util.tree_leaves(tree))
    except Exception:  # noqa: BLE001 — accounting must never break solves
        return 0


class XlaBackend:
    """Legacy scan backend (per-value count tables + gathers). Slow for
    wide hostname-keyed term spaces, but free of the planes layout's
    structural limits (e.g. tracked terms > padded nodes) — kept as the
    solve chain's last resort."""

    name = "xla-legacy"

    def prepare(self, cluster, batch):
        if cluster.sv_attached is not None:
            # silently ignoring shared-volume planes would let a shared
            # claim double-count; shared-volume epochs must solve on
            # the planes scan (or serial-fall-back loudly)
            raise ValueError(
                "legacy scan does not carry shared-volume planes")
        return (build_static(cluster, batch, device=True),
                build_state(cluster, batch, device=True))

    def solve_lazy(self, params, static, state, pod_ints, pod_floats):
        new_state, assignments = _solve_packed(
            static, state, pod_ints, pod_floats, params
        )
        return assignments, new_state

    @staticmethod
    def materialize(handle):
        return np.asarray(handle)

    def solve(self, params, static, state, pod_ints, pod_floats):
        h, new_state = self.solve_lazy(params, static, state, pod_ints,
                                       pod_floats)
        return self.materialize(h), new_state


def _mesh_width(n_devices: int) -> int:
    """Mesh node-axis width for the sharded tier: the largest power of
    two ≤ the visible device count. Pad buckets are multiples of 128
    lanes, so a power-of-two axis always divides the padded node count
    (a 6-wide mesh would trip the divisibility contract and demote on
    the very first rebuild)."""
    width = 1
    while width * 2 <= n_devices:
        width *= 2
    return width


def default_backend():
    """Backend tiering, mesh-aware since the sharded-by-default solve:

    - ``KTPU_SOLVER=xla|pallas|cpp`` pin the legacy single-device
      backends exactly as before;
    - ``KTPU_SOLVER=sharded`` forces the mesh backend over every
      visible device (a power-of-two mesh; even a 1-device mesh, for
      the shard_map-machinery control arm);
    - ``KTPU_SOLVER=auto`` — and UNSET on real multi-device hardware
      (tpu/gpu) — takes the mesh tier whenever more than one device is
      visible: the hardware, not the host, becomes the ceiling.
      On a CPU host the unset default keeps the single-device planes
      scan even when virtual devices are forced
      (``--xla_force_host_platform_device_count``): virtual host
      devices share the same silicon, so sharding there is a scaling
      test vehicle (bench/devscale set ``auto`` explicitly), not a
      production win — and the tier-1 suite must not silently pay
      mesh compile costs;
    - otherwise: Pallas kernel on TPU, native C++ planes solver when
      the library builds, else the gather-free XLA planes scan.

    A single visible device NEVER constructs a mesh on the auto/unset
    paths (guarded by tests/test_backend_guard.py): single-device
    startup pays zero mesh machinery."""
    import os

    import jax

    choice = os.environ.get("KTPU_SOLVER", "")
    if choice == "xla":
        from kubernetes_tpu.ops.pallas_solver import XlaPlanesBackend

        return XlaPlanesBackend()
    if choice == "cpp":
        from kubernetes_tpu.ops.native_backend import CppBackend

        return CppBackend()
    if choice == "pallas":
        from kubernetes_tpu.ops.pallas_solver import PallasBackend

        return PallasBackend(interpret=jax.default_backend() == "cpu")
    n_devices = jax.device_count()
    mesh_tier = (
        choice == "sharded"
        or (choice == "auto" and n_devices > 1)
        or (choice == "" and n_devices > 1
            and jax.default_backend() in ("tpu", "gpu"))
    )
    if mesh_tier:
        from kubernetes_tpu.parallel import ShardedBackend, make_mesh

        return ShardedBackend(make_mesh(_mesh_width(n_devices)))
    if jax.default_backend() == "tpu":
        from kubernetes_tpu.ops.pallas_solver import PallasBackend

        return PallasBackend()
    # gpu/metal/cpu: Mosaic does not lower there. Prefer the native C++
    # planes solver when the library builds, else the XLA planes scan.
    from kubernetes_tpu.ops import native_backend

    if native_backend.available():
        return native_backend.CppBackend()
    from kubernetes_tpu.ops.pallas_solver import XlaPlanesBackend

    return XlaPlanesBackend()


# beyond these per-axis sizes the pallas kernel's Python-unrolled
# constraint loops stop paying off (compile time and per-step vector-op
# count scale linearly in SC/T/U, while the XLA scan handles the same
# axes as single fused [K, N] ops)
PALLAS_MAX_SC = 8
PALLAS_MAX_TERMS = 8
PALLAS_MAX_PROFILES = 8

# rebuilds a demoted backend must survive before the preferred backend
# is retried (transient tunnel errors must not demote forever)
DEMOTION_RETRY_REBUILDS = 3


def _pallas_fits(batch) -> bool:
    return (
        batch.sc_counts.shape[0] <= PALLAS_MAX_SC
        and batch.term_counts.shape[0] <= PALLAS_MAX_TERMS
        and batch.static_masks.shape[0] <= PALLAS_MAX_PROFILES
        # shared-volume epochs need the sv planes (the planes scan,
        # the native C++ mirror, and the mesh-sharded scan carry them;
        # the pallas kernel doesn't)
        and getattr(batch, "pod_sv", None) is None
    )


class SolverSession:
    """Owns the device mirror for one scheduler's batch path."""

    def __init__(self, scheduler, params: SolverParams = SolverParams(),
                 max_batch: int = 4096, pad_nodes: int = 128,
                 backend=None):
        self.sched = scheduler
        self.params = params
        self.max_batch = max_batch
        self.pad_nodes = pad_nodes
        self.backend = backend or default_backend()
        # backend actually used for the current epoch (a wide constraint
        # space demotes pallas to the scan for that epoch only)
        self._active = self.backend
        # demotion is NOT permanent: a transient runtime error (TPU-tunnel
        # flake) looks the same as a compile failure from here, so after
        # DEMOTION_RETRY_REBUILDS successful rebuilds on the demoted
        # backend the preferred one gets another chance
        self._preferred = self.backend
        self._demote_cooldown = 0
        self._encoder: Optional[BatchEncoder] = None
        self._cluster: Optional[EncodedCluster] = None
        self._static = None   # device-resident solve-invariant arrays
        self._state = None    # device-resident dynamic state (carried)
        self._static_fp = None  # fingerprint of the resident static
        # host-side static predicate masks + the last batch's per-pod
        # profile indices: lets the sidecar synthesize per-node filter
        # statuses for device-declined pods without a serial re-run
        self._static_masks_host = None   # [U, N] bool
        self.last_profile_idx = None     # [B] int32
        self.last_inexpressible = None   # [B] bool
        self._last_seq: int = -1
        # node-SET epoch the resident encoding was built over. The
        # mutation arithmetic alone can be laundered by compensating
        # bumps; an encoding whose node columns describe another epoch
        # (chaos_nodes: mass node death) must rebuild, not keep
        # declining/misassigning against ghost nodes.
        self._node_epoch: int = -1
        self._poisoned = False
        self._warming = False
        # materializer for the LAST lazy solve's handle (None when the
        # result was returned eagerly, e.g. the rebuild path)
        self.last_materializer = None
        # newest-applied-event anchor the LAST staleness sample was
        # taken against: a retry cycle solving an UNCHANGED snapshot
        # accrues no new staleness debt and must not be sampled (a
        # quiet cluster — autoscale row waiting out node boot latency —
        # would otherwise read as an ever-staler snapshot and
        # false-flip the staleness SLO)
        self._staleness_anchor = 0.0
        # telemetry: how often the incremental path was taken
        self.incremental_hits = 0
        self.rebuilds = 0
        self.state_only_rebuilds = 0
        # pipeline stage handoff: an incremental solve dispatched while
        # the PREVIOUS lazy solve's handle was still unmaterialized
        # chained directly onto its in-flight state carry — the device
        # runs back-to-back batches with zero host round trip, and (on
        # the donating mesh tier) the carry consumed by solve N is
        # NEVER re-encoded or re-uploaded for N+1: XLA aliases it
        # straight into N+1's inputs. ``carry_chained`` counts those
        # dispatches; the differential guard and the sustained-arrival
        # cell read it to prove the pipeline actually pipelines.
        self.carry_chained = 0
        self._dispatch_seq = 0      # lazy handles handed out
        self._materialize_seq = 0   # lazy handles consumed
        # scheduling-cycle id stamped by the sidecar before each solve so
        # the per-cycle phase spans correlate with the pods' queue cycles
        self.trace_cycle = -1
        # optional device profiling (SURVEY.md section 5: JAX profiler /
        # xplane dumps per solve batch): KTPU_PROFILE_DIR starts a trace
        # at the first non-warming solve and stops it after
        # KTPU_PROFILE_BATCHES (default 5) solves
        import os

        self._profile_dir = os.environ.get("KTPU_PROFILE_DIR") or None
        try:
            self._profile_left = int(
                os.environ.get("KTPU_PROFILE_BATCHES", "5")
            )
        except ValueError:
            _logger.warning("invalid KTPU_PROFILE_BATCHES; profiling off")
            self._profile_left = 0
        if self._profile_left <= 0:
            self._profile_dir = None
        self._profiling = False
        # device-resident cluster mirror (KTPU_MIRROR, default on):
        # watch deltas journaled by the cache are SCATTERED into the
        # donated planes at the next solve instead of forcing a full
        # host encode. Constructed only when the preferred backend
        # exposes the scatter hooks (the legacy scan doesn't) and the
        # scheduler carries a journal-capable cache.
        self._mirror = None
        self._journal = None
        if (
            mirror_enabled()
            and hasattr(self.backend, "scatter_state_add")
            and hasattr(getattr(self.sched, "cache", None),
                        "attach_delta_journal")
        ):
            from kubernetes_tpu.ops.mirror import (
                DeltaJournal,
                DeviceClusterMirror,
            )

            self._journal = DeltaJournal()
            self.sched.cache.attach_delta_journal(self._journal)
            self._mirror = DeviceClusterMirror(self, self._journal)

    # ------------------------------------------------------------------
    def warm_pad(self, pods: List, pad: int) -> Optional[int]:
        """Compile the ``pad``-sized executable WITHOUT touching the
        state mirror: runs one solve against the resident static/state
        arrays and discards every output (jax arrays are immutable, so
        the live ``self._state`` is untouched and any pipelined lazy
        handle stays valid). The sidecar calls this between cycles when
        the latency tuner shrinks to a bucket that has never compiled —
        the compile must burn an un-measured moment, not a real batch's
        e2e latency. Returns the number of compile events devprof
        MEASURED during the warm (0 = the executable was already cached
        and no warm was actually needed — the sidecar's accounting is
        measured, not assumed), or None when there is no resident mirror
        to warm against (the next real solve is a rebuild, which
        compiles its own pad anyway)."""
        if self._state is None or self._encoder is None or \
                self._cluster is None:
            return None
        dp = get_devprof()
        rec = dp.begin_cycle(cycle=-1, pad=pad, real=len(pods),
                             warming=True) if dp.enabled else None
        try:
            pb = self._encoder.encode_pods_only(pods, pad)
            if pb is None or pb.requests.shape[1] != \
                    self._cluster.allocatable.shape[1]:
                dp.abort(rec)
                rec = None
                return None
            ints, floats = pack_podin(pb)
            dp.add_bytes("h2d", ints.nbytes + floats.nbytes)
            # a backend whose solve DONATES its state buffers (the
            # mesh-sharded tier) must warm against a disposable clone:
            # jax-array immutability no longer protects the resident
            # mirror once the executable aliases inputs into outputs
            state = self._state
            clone = getattr(self._active, "warm_state", None)
            if clone is not None and getattr(self._active, "donate",
                                             False):
                state = clone(state)
            t0 = time.monotonic()
            handle, _discarded_state = self._active.solve_lazy(
                self.params, self._static, state, ints, floats
            )
            t_disp = time.monotonic()
            out = self._active.materialize(handle)  # block: compile+run
            staging = self._take_staging_s()
            dp.phase("dispatch", max(0.0, t_disp - t0 - staging))
            dp.phase("block", time.monotonic() - t_disp + staging)
            self._flush_backend_bytes(dp)
            dp.add_bytes("d2h", int(getattr(out, "nbytes", 0)))
            # measured compile count when the listener is live; the
            # timing heuristic can only classify at cycle completion,
            # so without a listener the legacy one-warm-per-call
            # assumption stands in
            return rec["compiles"] \
                if rec is not None and dp.listener_active else 1
        except Exception:   # noqa: BLE001 — warming is advisory
            dp.abort(rec)
            rec = None
            return None
        finally:
            dp.end_cycle(rec)

    def invalidate(self) -> None:
        """Mark the device mirror diverged. Sticky until the next rebuild:
        a later ``note_committed`` must not re-validate (e.g. a host-
        rejected assignment the device already counted leaves the mirror
        wrong even when the mutation arithmetic works out)."""
        self._last_seq = -1
        self._poisoned = True

    def note_drift(self) -> None:
        """Snapshot-drift trigger (chaos_nodes): a commit-time guard
        just refused assignments because their target nodes died, were
        cordoned, or went unreachable after this encoding was built.
        Beyond invalidating, drop the static fingerprint — the NODE
        PLANES themselves are what drifted, so the next rebuild must
        re-encode and re-upload the static arrays rather than take the
        state-only path and keep solving against ghost columns (the
        mass-decline spin this exists to break)."""
        self.invalidate()
        self._static_fp = None

    def mirror_current(self) -> bool:
        """True when the device mirror is still consistent with the host
        cache RIGHT NOW (no unsanctioned mutations since it was last
        validated). The pipelined sidecar checks this before committing
        a batch solved one cycle earlier."""
        return (
            not self._poisoned
            and self._last_seq == self.sched.cache.mutation_seq
            and self._node_epoch == self.sched.cache.node_set_seq
        )

    def note_committed(self, expected_mutations: int, seq_before: int) -> None:
        """Called by the sidecar after committing a batch: the session
        stays valid only if the mirror was valid going INTO this batch
        (``_last_seq == seq_before`` — otherwise a zero-mutation batch
        would launder an earlier invalidation) and the cache saw exactly
        the expected number of mutations (one assume per committed pod)
        since ``seq_before``."""
        seq_now = self.sched.cache.mutation_seq
        if (
            not self._poisoned
            and self._last_seq == seq_before
            and seq_now == seq_before + expected_mutations
        ):
            self._last_seq = seq_now
        elif (
            self._mirror is not None
            and not self._poisoned
            and self._last_seq >= 0
        ):
            # mirror arm: unexpected-but-journaled mutations (serial
            # binds, external pod/node events, TTL expiry) no longer
            # force a rebuild — the anchor stays where the device state
            # is known-good and the next solve's catch-up scatters the
            # journal window on top. Anything the journal can't express
            # still reseeds there.
            pass
        else:
            self._last_seq = -1

    # ------------------------------------------------------------------
    def solve(self, pods: List, warming: bool = False, lazy: bool = False,
              incremental_only: bool = False, pad_to: Optional[int] = None,
              ) -> Optional[Tuple[object, EncodedCluster, int]]:
        """Solve one batch. Returns (assignments, cluster, seq_before)
        where assignments map batch index → node index in
        ``cluster.node_names`` (-1 = unschedulable on device).
        ``warming`` suppresses telemetry (metrics segments, rebuild
        counters) so JIT-compile time stays out of the measured series.
        With ``lazy`` the assignments are an opaque handle — pass it to
        ``materialize`` (captured via ``last_materializer``) later, so
        host work overlaps the asynchronously-dispatched device solve.
        With ``incremental_only`` the call returns None instead of
        rebuilding (the pipelined caller must commit its in-flight batch
        before a rebuild, or the fresh snapshot would miss it).
        ``pad_to`` overrides the padded batch shape (the sidecar's
        latency-budget chunking: the scan length — and so the per-batch
        device latency — is the PADDED size, not the real pod count;
        each distinct pad size is its own compiled executable)."""
        self._warming = warming
        self._profile_tick()
        pad = pad_to or self.max_batch
        seq_before = self.sched.cache.mutation_seq
        # mirror catch-up: a journaled mutation window since the last
        # validated seq is scattered into the resident planes, making
        # the incremental gate below pass — external churn stops
        # forcing rebuilds. Timed here, booked into the devprof cycle
        # once it opens (the scatter belongs to THIS solve's cycle).
        scatter_stash = None
        if (
            self._mirror is not None and self._state is not None
            and not self._poisoned
            and 0 <= self._last_seq != seq_before
            and self._node_epoch == self.sched.cache.node_set_seq
        ):
            t_sc = time.monotonic()
            applied = self._mirror.catch_up(self._last_seq, seq_before)
            if applied is not None:
                scatter_stash = (time.monotonic() - t_sc, applied)
                self._last_seq = seq_before
        if self._state is not None and seq_before == self._last_seq \
                and self._node_epoch == self.sched.cache.node_set_seq:
            dp = get_devprof()
            rec = dp.begin_cycle(
                cycle=self.trace_cycle, pad=pad, real=len(pods),
                warming=warming) if dp.enabled else None
            if not warming:
                self._note_staleness(rec, dp)
            if scatter_stash is not None:
                sc_s, sc_bytes = scatter_stash
                dp.phase("scatter", sc_s)
                if sc_bytes:
                    # the only remaining per-event h2d: index/value
                    # triples. Counted in solver_transfer_bytes_total
                    # (h2d) plus the scatter attribution ledger; never
                    # in the donated ledger.
                    dp.add_bytes("h2d", sc_bytes)
                    dp.add_bytes("scatter", sc_bytes)
                if not warming:
                    self._observe("scatter", sc_s)
            try:
                t0 = time.monotonic()
                pb = self._encoder.encode_pods_only(pods, pad)
                if pb is not None and pb.requests.shape[1] == \
                        self._cluster.allocatable.shape[1]:
                    self.last_profile_idx = pb.profile_idx
                    self.last_inexpressible = pb.inexpressible
                    t_pack = time.monotonic()
                    ints, floats = pack_podin(pb)
                    t_done = time.monotonic()
                    self._observe("encode", t_pack - t0, end_mono=t_pack)
                    self._observe("pack", t_done - t_pack,
                                  end_mono=t_done)
                    # devprof attribution: the pod-row delta encode is
                    # the drained pods' h2d prep — inherent per-batch
                    # work, booked under pack. The "encode" phase (and
                    # so encode_share) is reserved for cluster-plane
                    # builds, the stage the device mirror eliminates.
                    dp.phase("pack", t_done - t0)
                    dp.add_bytes("h2d", ints.nbytes + floats.nbytes)
                    # stage handoff: with the previous lazy handle
                    # still in flight, this dispatch chains onto its
                    # UNMATERIALIZED state carry — jax sequences the
                    # two solves on device with no host sync, and a
                    # donating backend aliases the consumed carry into
                    # this solve's inputs (never re-encoded host-side)
                    chained = self._dispatch_seq > self._materialize_seq
                    t0 = time.monotonic()
                    handle, self._state = self._active.solve_lazy(
                        self.params, self._static, self._state,
                        ints, floats
                    )
                    if chained and not warming:
                        self.carry_chained += 1
                    staging = self._take_staging_s()
                    dp.phase("dispatch",
                             max(0.0, time.monotonic() - t0 - staging))
                    if staging:
                        # synchronous host↔device plane staging (the
                        # un-donated arm): the device sat fed-or-idle on
                        # this copy — device wait, not dispatch work
                        dp.phase("block", staging)
                    self._flush_backend_bytes(dp)
                    if lazy:
                        self.last_materializer = \
                            self._timed_materializer(rec)
                    else:
                        t_b = time.monotonic()
                        handle = self._active.materialize(handle)
                        dp.phase("block", time.monotonic() - t_b)
                        dp.add_bytes(
                            "d2h", int(getattr(handle, "nbytes", 0)))
                        self.last_materializer = None
                    self._observe("device", time.monotonic() - t0)
                    dp.end_cycle(rec, pending_block=lazy)
                    if not self._warming:
                        self.incremental_hits += 1
                    return handle, self._cluster, seq_before
                # incremental encode fell through (epoch shape drift):
                # the record describes no solve — drop it rather than
                # pollute the cycle stream with an empty row
                dp.abort(rec)
            except BaseException:
                # encode/solve raised (the sidecar falls back to the
                # serial path): the record describes no completed solve,
                # and leaving it thread-local-active would misattribute
                # later compile events to a dead cycle
                dp.abort(rec)
                raise
        if incremental_only:
            return None
        # the rebuild path always solves eagerly (rebuilds are rare and
        # the caller just committed any in-flight batch anyway)
        return self._rebuild_and_solve(pods, seq_before, pad)

    def _note_staleness(self, rec, dp) -> None:
        """Snapshot-staleness SLI, once per solve cycle: age of the
        newest watch event reflected in the cache this encoding solves
        against (``SchedulerCache.last_event_ts``, stamped at store
        commit). Sampled only for cycles whose snapshot ADVANCED since
        the previous sample — a backoff-retry cycle over an unchanged
        snapshot (no events exist to reflect) is solving CURRENT truth,
        and counting its ever-growing event age would false-flip the
        staleness SLO during any event lull. Lands in the devprof cycle
        record (→ the bench row's ``freshness`` sub-object), the
        ``snapshot_staleness_seconds`` histogram (→ the SLO engine),
        and a cycle-correlated tracer instant so staleness is
        attributable per pod through the flight recorder."""
        try:
            ts = getattr(self.sched.cache, "last_event_ts", 0.0)
            if not ts or ts == self._staleness_anchor:
                return
            self._staleness_anchor = ts
            stale = max(0.0, time.time() - ts)
            dp.note_staleness(rec, stale)
            from kubernetes_tpu.metrics.freshness_metrics import (
                freshness_metrics,
            )

            fm = freshness_metrics()
            if fm.enabled:
                fm.snapshot_staleness_seconds.observe(stale)
                tracer = get_tracer()
                if tracer.enabled:
                    tracer.event("solve.staleness",
                                 cycle=self.trace_cycle,
                                 staleness_ms=round(stale * 1000, 2))
        except Exception:  # noqa: BLE001 — SLIs must never break solves
            pass

    def _flush_backend_bytes(self, dp, backend=None) -> None:
        """Book a self-accounting backend's pending transfer ledgers
        (real uploads/readbacks as h2d/d2h, donated resident planes in
        the excluded ``donated`` ledger) into the open devprof cycle.
        Called only AFTER a successful solve — the same
        charge-only-after-success rule the generic ``_tree_nbytes``
        accounting follows, so a failed chain link's upload never
        pollutes the cycle of the backend that actually solved."""
        take = getattr(backend or self._active, "take_transfer_bytes",
                       None)
        if take is None:
            return
        try:
            for direction, n in take().items():
                if n:
                    dp.add_bytes(direction, int(n))
        except Exception:  # noqa: BLE001 — accounting must never break
            pass

    def _take_staging_s(self, backend=None) -> float:
        """Consume a backend's synchronous host↔device staging seconds
        for the last solve (0.0 for backends without staging — only the
        un-donated sharded arm stages). Defaults to the ACTIVE backend;
        the rebuild chain passes its candidate explicitly (``_active``
        is only re-pointed after success). The caller subtracts this
        from its dispatch timing and books it as block: time spent
        feeding the device is device wait."""
        take = getattr(backend or self._active, "take_staging_s", None)
        if take is None:
            return 0.0
        try:
            return float(take())
        except Exception:  # noqa: BLE001 — accounting must never break
            return 0.0

    def _timed_materializer(self, rec):
        """Wrap the backend's materialize so a lazy solve's
        ``block_until_ready`` wait — which lands cycles later, inside
        the commit pipeline — is measured and attributed to the cycle
        that dispatched it (devprof ``note_block`` completes the record;
        a ``solve.block`` tracer span carries the same cycle id so
        ``/debug/trace`` shows the wait next to the dispatch). The
        wrapper also advances the dispatch/materialize sequence the
        ``carry_chained`` stage-handoff counter reads, so it is
        returned even with devprof off (``rec`` None — ``note_block``
        then no-ops); the residual cost is one closure per CYCLE."""
        mat = self._active.materialize
        self._dispatch_seq += 1
        token = self._dispatch_seq
        dp = get_devprof()

        def _timed(handle):
            t0 = time.monotonic()
            out = mat(handle)
            end = time.monotonic()
            if token > self._materialize_seq:
                self._materialize_seq = token
            try:
                # start_mono lets devprof compute overlap_s: the host
                # work performed between dispatch and this block is the
                # time the pipeline hid under the in-flight solve
                dp.note_block(rec, end - t0,
                              int(getattr(out, "nbytes", 0)),
                              start_mono=t0)
                tracer = get_tracer()
                if tracer.enabled:
                    tracer.record("solve.block", t0, end,
                                  cycle=rec["cycle"])
            except Exception:  # noqa: BLE001 — must never break commits
                pass
            return out

        return _timed

    # inputs whose equality makes the packed STATIC planes bit-identical
    _STATIC_FP_CLUSTER = ("allocatable", "max_pods", "topo_codes")
    _STATIC_FP_BATCH = (
        "static_masks", "static_scores", "sc_key_idx", "sc_max_skew",
        "sc_hard", "sc_domain", "term_key_idx",
    )

    def _static_fingerprint(self, cluster, batch):
        # sv_keys: the shared-volume slot mapping is part of the static
        # identity — a changed slot order re-keys every pod_sv index
        sv_keys = cluster.sv_keys if cluster.sv_keys is not None \
            else np.empty(0, dtype=np.int64)
        return (
            [np.asarray(getattr(cluster, k))
             for k in self._STATIC_FP_CLUSTER]
            + [np.asarray(getattr(batch, k))
               for k in self._STATIC_FP_BATCH]
            + [sv_keys],
            (cluster.resource_names, batch.num_values,
             cluster.num_real_nodes),
        )

    @staticmethod
    def _fingerprints_equal(a, b) -> bool:
        if a is None or b is None or a[1] != b[1]:
            return False
        return all(
            x.shape == y.shape and np.array_equal(x, y)
            for x, y in zip(a[0], b[0])
        )

    def _rebuild_and_solve(self, pods: List, seq_before: int,
                           pad: Optional[int] = None):
        if not self._warming:
            self.rebuilds += 1
        self._poisoned = False
        # a pending handle the sidecar discarded (mirror drift) is
        # never materialized; re-sync the stage-handoff sequence so the
        # dangling token can't make every later dispatch read as
        # chained onto a carry that no longer exists
        self._materialize_seq = self._dispatch_seq
        dp = get_devprof()
        rec = dp.begin_cycle(
            cycle=self.trace_cycle, pad=pad or self.max_batch,
            real=len(pods), warming=self._warming,
            rebuild="full") if dp.enabled else None
        if not self._warming:
            self._note_staleness(rec, dp)
        try:
            return self._rebuild_and_solve_inner(
                pods, seq_before, pad, dp, rec)
        except BaseException:
            # the solve chain exhausted (or a keyboard interrupt): the
            # record describes no completed solve
            dp.abort(rec)
            raise

    def _rebuild_and_solve_inner(self, pods: List, seq_before: int,
                                 pad: Optional[int], dp, rec):
        t0 = time.monotonic()
        # for the mirror's reseed accounting: a rebuild with resident
        # state is a re-seed (the mirror failed to keep up); the cold
        # start is just the seed
        mirror_cold = self._state is None
        # captured BEFORE the snapshot refresh: a node-set change that
        # races the rebuild bumps mutation_seq too, so the next solve
        # re-validates either way
        self._node_epoch = self.sched.cache.node_set_seq
        self.sched.algorithm.update_snapshot()
        self._encoder = BatchEncoder(
            self.sched.algorithm.snapshot, pad_nodes=self.pad_nodes,
            client=getattr(self.sched, "client", None),
            # sharded encode: split the node-column fill by the SAME
            # shard boundaries the mesh solve uses, so a 50k-node plane
            # never serializes on one host thread before upload
            node_shards=getattr(self.backend, "encode_shards", 1),
        )
        cluster, batch = self._encoder.encode(
            pods, pad_pods=pad or self.max_batch
        )
        self._cluster = cluster
        self._static_masks_host = batch.static_masks
        self.last_profile_idx = batch.profile_idx
        self.last_inexpressible = batch.inexpressible
        t_pack = time.monotonic()
        ints, floats = pack_podin(batch)
        t_done = time.monotonic()
        self._observe("encode", t_pack - t0, end_mono=t_pack)
        self._observe("pack", t_done - t_pack, end_mono=t_done)
        dp.phase("encode", t_pack - t0)
        dp.phase("pack", t_done - t_pack)
        dp.add_bytes("h2d", ints.nbytes + floats.nbytes)

        # a demoted backend earns retries of the preferred one FIRST —
        # the state-only fast path below must not starve the cooldown
        # (transient device errors would pin the slower backend forever)
        if self.backend is not self._preferred:
            self._demote_cooldown -= 1
            if self._demote_cooldown <= 0:
                self.backend = self._preferred

        # state-only rebuild: when the mutation that invalidated the
        # mirror touched only DYNAMIC state (mass preemption's victim
        # deletions, serial binds), the packed static planes are
        # bit-identical to the resident ones — re-upload just the state
        # planes and keep the device-resident static (halves the
        # per-round host→device traffic on the rebuild-heavy paths)
        fp = self._static_fingerprint(cluster, batch)
        if (
            self._static is not None
            and self._active is self.backend
            and hasattr(self._active, "prepare_state_only")
            and self._fingerprints_equal(fp, self._static_fp)
        ):
            try:
                if rec is not None:
                    rec["rebuild"] = "state_only"
                t0 = time.monotonic()
                state = self._active.prepare_state_only(cluster, batch)
                t_disp = time.monotonic()
                handle, self._state = self._active.solve_lazy(
                    self.params, self._static, state, ints, floats
                )
                t_block = time.monotonic()
                out = self._active.materialize(handle)
                t_end = time.monotonic()
                staging = self._take_staging_s()
                dp.phase("dispatch",
                         max(0.0, t_block - t_disp - staging))
                dp.phase("block", t_end - t_block + staging)
                # bytes accounted only after the solve SUCCEEDS (same
                # rule as the chain loop below): a failed state-only
                # attempt falls through to the full path, which charges
                # its own static+state upload for this cycle. A
                # self-accounting backend (sharded tier) reports its
                # real uploads via the pending-ledger hand-over —
                # _tree_nbytes would count donated device-resident
                # buffers as shipped.
                if getattr(self._active, "self_accounting", False):
                    self._flush_backend_bytes(dp)
                else:
                    dp.add_bytes("h2d", _tree_nbytes(state))
                dp.add_bytes("d2h", int(getattr(out, "nbytes", 0)))
                self.last_materializer = None
                self._observe("device", t_end - t0)
                dp.end_cycle(rec)
                self._last_seq = seq_before
                if not self._warming:
                    self.state_only_rebuilds += 1
                if self._mirror is not None:
                    self._mirror.note_seeded(mirror_cold, self._warming)
                return out, cluster, seq_before
            except Exception:  # noqa: BLE001 — fall back to full rebuild
                _logger.exception("state-only rebuild failed; full path")
                if rec is not None:
                    rec["rebuild"] = "full"
        self._static_fp = fp
        from kubernetes_tpu.ops.pallas_solver import XlaPlanesBackend

        # solve chain (clean-fallback contract, like an IsIgnorable
        # extender): preferred backend when the space fits it, then the
        # gather-free planes scan, then the legacy scan — which has no
        # structural layout limits and runs on every platform
        if self.backend.name == "xla-legacy":   # demoted all the way down
            chain = [self.backend]
        else:
            chain = []
            if self.backend.name == "pallas":
                if _pallas_fits(batch):
                    chain.append(self.backend)
            else:
                chain.append(self.backend)       # cpp or planes scan
            if self.backend.name != "xla-planes":
                chain.append(XlaPlanesBackend())
            chain.append(XlaBackend())
        if cluster.sv_attached is not None:
            # shared-volume epochs solve on the backends that carry the
            # sv planes (the planes scan, the native C++ mirror, and
            # the mesh-sharded scan) — a structural routing decision
            # like _pallas_fits, NOT an exception: letting pallas/
            # legacy raise here would demote the preferred backend for
            # sv-free epochs too and log a designed-for case as failure
            chain = [b for b in chain
                     if b.name in ("xla-planes", "cpp", "sharded")] \
                or [XlaPlanesBackend()]
        t0 = time.monotonic()
        for i, backend in enumerate(chain):
            try:
                t0 = time.monotonic()
                self._static, state = backend.prepare(cluster, batch)
                t_disp = time.monotonic()
                handle, self._state = backend.solve_lazy(
                    self.params, self._static, state, ints, floats
                )
                t_block = time.monotonic()
                out = backend.materialize(handle)
                # phases recorded only for the backend that SUCCEEDED —
                # a failed chain link's dispatch attempt must not read
                # as device time of the solve that actually ran
                staging = self._take_staging_s(backend)
                dp.phase("dispatch",
                         max(0.0, t_block - t_disp - staging))
                dp.phase("block",
                         time.monotonic() - t_block + staging)
                if getattr(backend, "self_accounting", False):
                    self._flush_backend_bytes(dp, backend)
                else:
                    dp.add_bytes("h2d", _tree_nbytes(self._static)
                                 + _tree_nbytes(state))
                dp.add_bytes("d2h", int(getattr(out, "nbytes", 0)))
                self._active = backend
                self.last_materializer = None  # already materialized
                break
            except Exception:
                if i == len(chain) - 1:
                    raise
                _logger.exception(
                    "%s solve backend failed; trying %s",
                    backend.name, chain[i + 1].name,
                )
                if backend is self.backend:
                    # don't re-pay a failing compile on every rebuild —
                    # but retry the preferred backend after a few
                    # successful rebuilds (the failure may be transient)
                    self.backend = chain[i + 1]
                    self._demote_cooldown = DEMOTION_RETRY_REBUILDS
        self._observe("device", time.monotonic() - t0)
        dp.end_cycle(rec)
        # valid-until-next-mutation; the sidecar's note_committed refines
        self._last_seq = seq_before
        if self._mirror is not None:
            self._mirror.note_seeded(mirror_cold, self._warming)
        return out, cluster, seq_before

    @property
    def static_masks_host(self):
        """Host copy of the current epoch's [U, N] static predicate
        masks (None before the first rebuild)."""
        return self._static_masks_host

    def static_mask_for(self, batch_index: int):
        """Host-side static predicate mask ([num_real_nodes] bool) for the
        given pod of the LAST solved batch, or None when unavailable.
        False = the node fails a node-static predicate (selector/affinity,
        nodeName, taints, unschedulable) — UnschedulableAndUnresolvable in
        reference terms; True = only dynamic predicates failed."""
        if (
            self._static_masks_host is None
            or self.last_profile_idx is None
            or self._cluster is None
            or batch_index >= len(self.last_profile_idx)
        ):
            return None
        u = self.last_profile_idx[batch_index]
        return self._static_masks_host[u][: self._cluster.num_real_nodes]

    def _profile_tick(self) -> None:
        if self._profile_dir is None or self._warming:
            return
        import jax

        try:
            if not self._profiling:
                jax.profiler.start_trace(self._profile_dir)
                self._profiling = True
            elif self._profile_left <= 0:
                self.finish_profiling()
                return
            self._profile_left -= 1
        except Exception:  # pragma: no cover — profiling must never break solves
            _logger.exception("solver profiling failed; disabled")
            self._profile_dir = None

    def finish_profiling(self) -> None:
        """Stop and flush an in-flight profiler trace (also called from
        the sidecar's shutdown so short runs still get their dump)."""
        if not self._profiling:
            return
        import jax

        try:
            jax.profiler.stop_trace()
            _logger.info("solver profile trace written")
        except Exception:  # pragma: no cover
            _logger.exception("solver profile stop failed")
        self._profiling = False
        self._profile_dir = None

    def _observe(self, segment: str, seconds: float,
                 end_mono: Optional[float] = None) -> None:
        if self._warming:
            return
        try:
            self.sched.metrics.batch_solve_duration.observe(seconds, segment)
        except Exception:  # pragma: no cover — metrics must never break solves
            pass
        # per-cycle solver phase span (solve.pack/encode/device): the
        # latency-breakdown backbone the bench diag, /metrics histogram,
        # and Perfetto dumps all read from. ``end_mono`` places a phase
        # that ended BEFORE this call correctly on the dump's timeline
        # (deriving start from observe time would shift it late).
        try:
            tracer = get_tracer()
            if tracer.enabled:
                end = end_mono if end_mono is not None \
                    else time.monotonic()
                tracer.record(f"solve.{segment}", end - seconds, end,
                              cycle=self.trace_cycle)
        except Exception:  # pragma: no cover
            pass
