"""Device batch path: snapshot/pod encoders and the JAX solvers.

This is the TPU-native replacement for the reference's hot loop: the
per-node goroutine fan-out (``parallelize.Until``, 16 workers) becomes
dense vector ops over the whole node axis, and the 30k sequential
``scheduleOne`` cycles become one ``lax.scan`` commit (serial-equivalent)
or conflict-resolution rounds on device (SURVEY.md section 2.5/7).

Division of labor (deliberate, TPU-first):
- **Host** (``encode.py``): the irregular, string-y, data-dependent work —
  label-selector matching, taint/toleration profiles, topology-value
  coding. All of it is O(distinct-profiles x nodes), tiny next to the
  O(pods x nodes) math.
- **Device** (``solver.py``): everything O(pods x nodes) or that mutates
  during the batch — capacity fit, skew counts, (anti-)affinity domain
  counts, scores, and the assignment itself. Static shapes, int32/f32,
  one-hot segment updates; no data-dependent Python control flow.
"""

from kubernetes_tpu.ops import jax_setup  # noqa: F401  (must precede first jit)
from kubernetes_tpu.ops.encode import BatchEncoder, EncodedBatch, EncodedCluster
from kubernetes_tpu.ops.solver import solve_scan, SolverParams
