"""ctypes bridge to the native C++ planes solver (kubernetes_tpu.native).

Same backend interface and planes layout as the JAX backends; state is
host numpy mutated in place by the library, so the cross-batch carry is
free. Serves as the CPU-native solve path and as an independent
implementation for differential testing of the device kernels.
"""

from __future__ import annotations

import ctypes

import numpy as np

from kubernetes_tpu import native
from kubernetes_tpu.ops.pallas_solver import (
    PState,
    _state_planes,
    prepare,
)
from kubernetes_tpu.ops.solver import SolverParams

_I32P = ctypes.POINTER(ctypes.c_int32)
_F32P = ctypes.POINTER(ctypes.c_float)


def available() -> bool:
    return native.load() is not None


class CppBackend:
    """Native solve backend (see session.py for the chain)."""

    name = "cpp"

    def __init__(self):
        self._lib = native.load()
        if self._lib is None:
            raise RuntimeError("native solver library unavailable")

    def prepare(self, cluster, batch):
        return prepare(cluster, batch, device=False)

    def solve_lazy(self, params, pstatic, pstate, pod_ints, pod_floats):
        """The native solver is synchronous; lazy == eager here."""
        return self.solve(params, pstatic, pstate, pod_ints, pod_floats)

    @staticmethod
    def materialize(handle):
        return handle

    # -------- device-resident mirror scatter hooks (ops.mirror): the
    # planes are host numpy mutated in place, so a "scatter" is a fancy
    # index update and zero bytes cross any link
    @staticmethod
    def scatter_state_add(pstate, rows, cols, vals):
        planes = pstate.planes
        flat = planes.reshape(planes.shape[0], -1)
        np.add.at(flat, (rows, cols), vals)
        return pstate, 0

    @staticmethod
    def scatter_static_set(pstatic, rows, cols, vals):
        flat = pstatic.ints.reshape(pstatic.ints.shape[0], -1)
        flat[rows, cols] = vals
        return pstatic, 0

    def solve(self, params: SolverParams, pstatic, pstate, pod_ints,
              pod_floats):
        planes = pstate.planes  # [CD, NB, 128] int32, C-contiguous
        n = planes.shape[1] * planes.shape[2]
        sv = pstatic.sv
        do, _ = _state_planes(pstatic.r, pstatic.sc, pstatic.t, sv)
        b, c_cols = pod_ints.shape
        expected = pstatic.r + 4 + 2 * pstatic.sc + 3 * pstatic.t \
            + (2 if sv else 0)
        if c_cols != expected:
            # mirror _unpack_podin's loud failure: misaligned columns
            # would silently corrupt every assignment
            raise ValueError(
                f"packed pod stream width {c_cols} does not match the "
                f"static constraint space (expected {expected})"
            )
        assignments = np.empty(b, dtype=np.int32)
        weights = np.array(
            [params.balanced_weight, params.least_weight,
             params.spread_weight, params.affinity_weight,
             params.static_weight],
            dtype=np.float32,
        )
        pod_ints = np.ascontiguousarray(pod_ints, dtype=np.int32)
        pod_floats = np.ascontiguousarray(pod_floats, dtype=np.float32)
        totals = planes[do["totals"]].reshape(-1)  # flat [:t] slots
        rc = self._lib.ktpu_solve(
            pstatic.ints.ctypes.data_as(_I32P),
            pstatic.f32s.ctypes.data_as(_F32P),
            np.ascontiguousarray(
                pstatic.sc_meta, dtype=np.int32
            ).ctypes.data_as(_I32P),
            planes.ctypes.data_as(_I32P),
            totals.ctypes.data_as(_I32P),
            pod_ints.ctypes.data_as(_I32P),
            pod_floats.ctypes.data_as(_F32P),
            assignments.ctypes.data_as(_I32P),
            weights.ctypes.data_as(_F32P),
            pstatic.r, pstatic.sc, pstatic.t, pstatic.u, pstatic.v,
            n, b, c_cols, sv,
        )
        if rc != 0:
            raise RuntimeError(f"ktpu_solve failed (rc={rc})")
        return assignments, PState(planes=planes)
