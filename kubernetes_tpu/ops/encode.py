"""Host-side encoding: Snapshot + pod batch → dense device arrays.

With the device mirror attached (``ops/mirror.py``), the full
cluster-plane build below runs only on cold start and on reseed
(journal gaps / inexpressible deltas) — steady state scatters watch
deltas into the resident planes and the per-batch work reduces to the
pod-row delta encode, which is the drained pods' own h2d prep.

The reference's PreFilter phase builds per-pod maps over all nodes
(``interpodaffinity/filtering.go:162-235``, ``podtopologyspread/
filtering.go:198-273``); this encoder materializes the same information
once per batch as tensors:

- node capacity/usage matrices ``[N, R]`` (int32: milli-CPU, KiB memory,
  KiB ephemeral, whole-unit scalars),
- topology value codes ``[N, K]`` per tracked topology key,
- per *static profile* node masks ``[U, N]`` — a profile is the tuple of a
  pod's node-static predicates (nodeName, nodeSelector, required node
  affinity, tolerations, unschedulable) evaluated with the SAME host
  plugin code the serial path runs, guaranteeing differential equality,
- tracked spread-constraint count matrices ``[SC, V]`` and per-pod match
  vectors,
- tracked (anti-)affinity term count/owner matrices ``[T, V]`` and
  membership masks.

Volume feasibility is tensorized (VERDICT r2 #1; reference
``plugins/volumebinding/volume_binding.go:82-269``, ``volumezone/
volume_zone.go``, ``nodevolumelimits/csi.go``): a pod whose PVCs are all
BOUND is expressible — its PV node-affinity/zone feasibility folds into
the static profile mask (computed with the real host plugins), and CSI
attach limits become extra resource columns (one per CSINode-limited
driver) so in-batch attach consumption re-masks exactly like CPU/memory.
Only Reserve/PreBind statefulness (assume/commit of UNBOUND matches)
stays host-side.

Pods the tensor model cannot express (unbound PVC volumes, shared RWX/ROX
claims, inline cloud-disk volumes, host ports, extender interest) are
flagged ``inexpressible`` and fall back to the serial path — the
clean-fallback contract.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Tuple

import numpy as np

from kubernetes_tpu.api import labels as labelslib
from kubernetes_tpu.api.types import CPU, EPHEMERAL_STORAGE, MEMORY, PODS, Pod
from kubernetes_tpu.scheduler.framework.cycle_state import CycleState
from kubernetes_tpu.scheduler.framework.plugins import mesh_locality
from kubernetes_tpu.scheduler.framework.plugins.helpers import (
    pod_matches_node_selector_and_affinity,
)
from kubernetes_tpu.scheduler.framework.plugins.node_unschedulable import (
    NodeUnschedulable,
)
from kubernetes_tpu.scheduler.framework.plugins.taint_toleration import (
    TaintToleration,
)
from kubernetes_tpu.scheduler.snapshot import Snapshot
from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo, Resource

HOSTNAME_KEY = "kubernetes.io/hostname"

# base resource columns; scalar/extended resources get appended per batch
BASE_RESOURCES = 3  # cpu (milli), memory (KiB), ephemeral (KiB)

# attach-limit resource columns (one per CSI driver with a CSINode
# limit) live in a reserved namespace so they can never collide with a
# real extended-resource name
ATTACH_COL_PREFIX = "attachable#csi#"
# a node/driver without a published limit is unconstrained; the sentinel
# must survive int32 arithmetic over a full batch of subtractions
NO_LIMIT = 1_000_000_000

# access modes implying a volume may be shared by multiple pods; the
# attach-column model counts per-pod distinct volumes and would
# double-count a share landing twice on one node, so such pods keep the
# host path (csi.go counts len(in_use | wanted) — set semantics)
SHARED_ACCESS_MODES = ("ReadWriteMany", "ReadOnlyMany")


def _resource_row(r: Resource, names: List[str]) -> List[int]:
    row = [r.milli_cpu, _kib(r.memory), _kib(r.ephemeral_storage)]
    for name in names[BASE_RESOURCES:]:
        row.append(r.scalar_resources.get(name, 0))
    return row


def _kib(b: int) -> int:
    return -((-b) // 1024)


def _round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


# below this many real nodes a sharded fill costs more in thread
# handoff than it wins — the per-node plugin loop is microseconds there
ENCODE_SHARD_MIN_NODES = 512


def _node_slices(n: int, shards: int) -> List[slice]:
    """Contiguous node-column ranges, one per encode worker — the SAME
    even split the mesh's NamedSharding uses for the node axis, so each
    worker emits exactly the columns one device shard will hold."""
    if shards <= 1 or n <= 0:
        return [slice(0, n)]
    step = -(-n // shards)
    return [slice(s, min(s + step, n)) for s in range(0, n, step)]


def _constraint_key(pod: Pod, c, sel: labelslib.Selector) -> tuple:
    """Dedup identity of a topology-spread constraint. Shared by the full
    and incremental encoders — the two must never diverge or incremental
    batches would map pods onto the wrong tracked constraint."""
    return (
        c.topology_key, c.max_skew,
        c.when_unsatisfiable == "DoNotSchedule",
        pod.namespace, repr(sel),
    )


def _term_key(t) -> tuple:
    """Dedup identity of an (anti-)affinity term (same sharing contract)."""
    return (t.topology_key, repr(t.selector), tuple(sorted(t.namespaces)))


def _simple_label_eq(selector: labelslib.Selector):
    """(key, value) when the selector is a single ``key IN (value)``
    requirement — the overwhelmingly common shape — else None."""
    reqs = selector.requirements
    if (
        not getattr(selector, "_nothing", False)
        and len(reqs) == 1
        and reqs[0].operator == labelslib.IN
        and len(reqs[0].values) == 1
    ):
        return (reqs[0].key, reqs[0].values[0])
    return None


def _build_match_index(items):
    """Split tracked constraints/terms into an inverted index of simple
    single-label selectors ((key, value) → [(idx, item)]) plus the
    complex remainder. Filling per-pod match masks via the index is
    O(pod labels) instead of O(tracked items): workloads with many
    modulo-k groups (e.g. 100 anti-affinity colors) otherwise spend
    longer matching selectors on the host than solving on device."""
    simple: Dict[tuple, list] = {}
    complex_items = []
    for idx, item in enumerate(items):
        kv = _simple_label_eq(item.selector)
        if kv is None:
            complex_items.append((idx, item))
        else:
            simple.setdefault(kv, []).append((idx, item))
    return simple, complex_items


@dataclass
class _TrackedConstraint:
    """One distinct topology-spread constraint shared by batch pods."""

    key_idx: int
    max_skew: int
    selector: labelslib.Selector
    namespace: str
    hard: bool  # DoNotSchedule vs ScheduleAnyway

    def matches(self, pod: Pod) -> bool:
        return pod.namespace == self.namespace and self.selector.matches(
            pod.metadata.labels
        )


@dataclass
class _TrackedTerm:
    """One distinct (anti-)affinity term."""

    key_idx: int
    selector: labelslib.Selector
    namespaces: frozenset

    def matches(self, pod: Pod) -> bool:
        return pod.namespace in self.namespaces and self.selector.matches(
            pod.metadata.labels
        )


@dataclass
class EncodedCluster:
    """Node-side arrays (all numpy; shipped to device by the solver)."""

    node_names: List[str]
    num_real_nodes: int
    resource_names: List[str]
    allocatable: np.ndarray        # [N, R] int32
    requested: np.ndarray          # [N, R] int32
    nonzero_requested: np.ndarray  # [N, 2] int32 (cpu milli, mem KiB) for scoring
    pod_count: np.ndarray          # [N] int32
    max_pods: np.ndarray           # [N] int32
    topo_keys: List[str] = field(default_factory=list)
    topo_codes: Optional[np.ndarray] = None   # [N, K] int32, V = missing
    topo_num_values: Optional[np.ndarray] = None  # [K] int32
    # shared-volume attach planes (VERDICT r4 next #5): slot s of a
    # shared CSI volume; sv_attached[s, n] = 1 when that volume is
    # already attached on node n — a pod re-using it there consumes NO
    # further attach budget (csi.go len(in_use | wanted) set semantics,
    # tensorized as conditional per-node demand carried in solver state)
    sv_attached: Optional[np.ndarray] = None  # [SV, N] int32 (0/1)
    sv_keys: Optional[np.ndarray] = None      # [SV] int64 stable hashes


@dataclass
class EncodedBatch:
    """Pod-side arrays + tracked dynamic constraint state."""

    pods: List[Pod]
    num_real_pods: int
    requests: np.ndarray           # [B, R] int32
    nonzero_requests: np.ndarray   # [B, 2] int32
    profile_idx: np.ndarray        # [B] int32 into static masks
    static_masks: np.ndarray       # [U, N] bool — node-static predicates
    affinity_masks: np.ndarray     # [U, N] bool — nodeSelector/affinity only
    static_scores: np.ndarray      # [U, N] float32 — static score plugins
    inexpressible: np.ndarray      # [B] bool — pod must use serial path

    # spread constraints
    sc_key_idx: np.ndarray         # [SC] int32
    sc_max_skew: np.ndarray        # [SC] int32
    sc_hard: np.ndarray            # [SC] bool
    sc_counts: np.ndarray          # [SC, V+1] int32 (existing matching pods)
    sc_domain: np.ndarray          # [U, SC, V+1] bool (eligible domains)
    pod_sc: np.ndarray             # [B, SC] bool — constraint belongs to pod
    pod_sc_match: np.ndarray       # [B, SC] bool — pod counts toward constraint

    # (anti-)affinity terms
    term_key_idx: np.ndarray       # [T] int32
    term_counts: np.ndarray        # [T, V+1] int32 (existing matched pods)
    term_owners: np.ndarray        # [T, V+1] int32 (existing anti-term owners)
    match_by: np.ndarray           # [B, T] bool — pod matched by term
    own_aff: np.ndarray            # [B, T] bool — pod requires term (affinity)
    own_anti: np.ndarray           # [B, T] bool — pod requires term (anti)
    pref_weight: np.ndarray        # [B, T] float32 — preferred term weights

    num_values: int                # V (shared topo-value space size)
    # per-pod shared-volume reference: [B, 2] int32 (slot or SV
    # sentinel, attach resource column); None when the epoch has no
    # shared CSI volumes (layout & compiled shapes identical to before)
    pod_sv: Optional[np.ndarray] = None


@dataclass
class EncodedPodBatch:
    """Pod-side-only arrays for an incremental batch against an existing
    encoding space (the device already holds the cluster/static arrays and
    the carried dynamic count state)."""

    pods: List[Pod]
    num_real_pods: int
    requests: np.ndarray           # [B, R] int32
    nonzero_requests: np.ndarray   # [B, 2] int32
    profile_idx: np.ndarray        # [B] int32
    inexpressible: np.ndarray      # [B] bool
    pod_sc: np.ndarray             # [B, SC] bool
    pod_sc_match: np.ndarray       # [B, SC] bool
    match_by: np.ndarray           # [B, T] bool
    own_aff: np.ndarray            # [B, T] bool
    own_anti: np.ndarray           # [B, T] bool
    pref_weight: np.ndarray        # [B, T] float32
    pod_sv: Optional[np.ndarray] = None   # [B, 2] int32


class BatchEncoder:
    """Encodes one (snapshot, pod batch) pair. After a full ``encode`` the
    encoder retains the *encoding space* — resource columns, topology-key/
    value codes, tracked constraints/terms, static profiles — so later
    batches whose pods fit the same space can be encoded pod-side-only
    (``encode_pods_only``) against device-resident cluster state (the
    Generation-LRU of the device mirror, SURVEY.md section 7 hard part 1)."""

    def __init__(self, snapshot: Snapshot, pad_nodes: int = 128,
                 client=None, extra_nodes: Optional[List] = None,
                 node_shards: int = 1):
        self.snapshot = snapshot
        # sharded encode stage (the mesh-native planes contract): the
        # node-column fill — resource rows and the per-profile static
        # predicate/score plugin sweeps, the O(U × N) host cost of a
        # rebuild — splits into ``node_shards`` contiguous column
        # ranges, the SAME even split the solve mesh's NamedSharding
        # uses, and runs on an encode worker pool. Workers write
        # disjoint column slices of preallocated arrays (deterministic:
        # no ordering-dependent state crosses a shard boundary), so a
        # 50k-node plane is emitted per-shard instead of serializing on
        # one host thread before upload. ``node_shards=1`` (every
        # non-mesh backend) is the exact serial path.
        self.node_shards = max(1, int(node_shards))
        self.node_infos = [ni for ni in snapshot.list() if ni.node is not None]
        # virtual node columns (the cluster autoscaler's what-if hook):
        # hypothetical template nodes appended AFTER the snapshot's real
        # nodes, encoded with the same host plugin code — static masks,
        # taints, topology codes all behave as if the node existed. The
        # caller identifies their columns as the last len(extra_nodes)
        # entries of cluster.node_names (ops/solver.py solve_whatif then
        # score-penalizes or disables them).
        self.num_snapshot_nodes = len(self.node_infos)
        if extra_nodes:
            for node in extra_nodes:
                ni = NodeInfo()
                ni.set_node(node)
                self.node_infos.append(ni)
        self.pad_nodes = pad_nodes
        self._client = client
        self._taint_plugin = TaintToleration()
        self._unsched_plugin = NodeUnschedulable()
        # CSI attach-limit columns: frozen per epoch (CSINode events
        # invalidate the session, so the set cannot drift mid-epoch)
        self._attach_drivers: List[str] = []
        self._attach_col: Dict[str, int] = {}
        # memoized pvc -> frozenset((driver, volume-key)) resolution
        self._pod_attach_cache: Dict[str, frozenset] = {}
        # per-epoch wfc_class_batchable verdicts (PV/SC/CSINode events
        # invalidate the session before the pool property can drift)
        self._wfc_cache: Dict = {}
        # (driver, volume) pairs already attached somewhere — by
        # existing pods (full encode) or earlier batch pods this epoch.
        # A pod re-using one of these rides the serial path: csi.go
        # counts len(in_use | wanted) (set semantics), the additive
        # column model would double-count the share and diverge.
        self._attached_volumes: set = set()
        # SHARED-volume slots: (driver, volume) -> slot. Shared claims
        # get a per-volume attach plane in solver state instead of the
        # additive column demand — their demand is per-NODE conditional
        # (1 only where the volume isn't attached yet). Enumerated from
        # the cluster's PVs at full encode; a pod whose shared volume
        # isn't slotted forces a rebuild (encode_pods_only → None).
        self._sv_slots: Dict[tuple, int] = {}
        self._sv_keys: List[tuple] = []
        self._sv_pad: int = 0
        self._vol_shared_cache: Dict[str, bool] = {}
        # encoding space retained by the last full encode()
        self._resource_names: Optional[List[str]] = None
        self._key_index: Optional[Dict[str, int]] = None
        self._con_index: Optional[Dict[tuple, int]] = None
        self._constraints: Optional[List[_TrackedConstraint]] = None
        self._term_index: Optional[Dict[tuple, int]] = None
        self._terms: Optional[List[_TrackedTerm]] = None
        self._profiles: Optional[Dict[tuple, int]] = None
        self._num_values: int = 0
        self._con_match_idx = ({}, [])
        self._term_match_idx = ({}, [])
        # delta-column pod-plane pool (streaming-scheduler encode
        # stage): the padded pod-side matrices stay RESIDENT between
        # batches, keyed by their shape tuple, and each encode zeroes
        # only the rows the previous batch dirtied before filling the
        # new batch's rows — a b_pad-sized allocation per cycle becomes
        # an O(real rows) touch. Safe to reuse while a solve is in
        # flight because ``pack_podin`` COPIES every pooled array into
        # the packed upload buffer before dispatch (np.concatenate /
        # astype); the arrays a consumer retains past the call
        # (profile_idx, inexpressible — the sidecar carries them in its
        # pending commit dict) and the one ``pack_podin`` returns as a
        # no-copy view (pref_weight) are deliberately allocated fresh
        # every batch and never pooled.
        self._pod_plane_pool: Dict[tuple, Dict] = {}

    # ------------------------------------------------------------------
    def _sharding_active(self) -> bool:
        return (self.node_shards > 1
                and len(self.node_infos) >= ENCODE_SHARD_MIN_NODES)

    def _run_encode_workers(self, tasks: List) -> None:
        """Run zero-arg encode tasks (each owning a disjoint node-column
        slice) on the worker pool; exceptions propagate to the caller
        exactly like the serial loop's would."""
        tasks = list(tasks)
        if len(tasks) <= 1:
            for t in tasks:
                t()
            return
        with ThreadPoolExecutor(
                max_workers=min(len(tasks), self.node_shards)) as pool:
            for f in [pool.submit(t) for t in tasks]:
                f.result()

    def _for_node_shards(self, fill) -> None:
        """Apply ``fill(node_slice)`` to every node-column shard —
        concurrently when sharding is active, else one full-range call
        (the exact serial path)."""
        n = len(self.node_infos)
        if not self._sharding_active():
            fill(slice(0, n))
            return
        self._run_encode_workers(
            [partial(fill, sl)
             for sl in _node_slices(n, self.node_shards)])

    def encode(self, pods: List[Pod], pad_pods: int = 64) -> Tuple[
        EncodedCluster, EncodedBatch
    ]:
        nis = self.node_infos
        n_real = len(nis)
        # coarse node buckets: few distinct compiled shapes (each XLA
        # binary is reused via the persistent cache), bounded padding waste.
        # Above 1024 nodes the bucket stays a multiple of pad_nodes so the
        # sharded solver's divisibility contract (pad_nodes is chosen as a
        # multiple of the mesh nodes axis) still holds.
        gran = (
            self.pad_nodes if n_real <= 1024
            else _round_up(512, self.pad_nodes)
        )
        n_pad = max(_round_up(max(n_real, 1), gran), self.pad_nodes)

        pod_infos = [PodInfo.of(p) for p in pods]
        resource_names = self._collect_resource_names(pod_infos)
        r = len(resource_names)

        allocatable = np.zeros((n_pad, r), dtype=np.int32)
        requested = np.zeros((n_pad, r), dtype=np.int32)
        nonzero_req = np.zeros((n_pad, 2), dtype=np.int32)
        pod_count = np.zeros(n_pad, dtype=np.int32)
        max_pods = np.zeros(n_pad, dtype=np.int32)
        def fill_node_rows(sl: slice) -> None:
            for i in range(sl.start, sl.stop):
                ni = nis[i]
                allocatable[i] = _resource_row(ni.allocatable,
                                               resource_names)
                requested[i] = _resource_row(ni.requested, resource_names)
                nonzero_req[i] = (
                    ni.non_zero_requested.milli_cpu,
                    _kib(ni.non_zero_requested.memory),
                )
                pod_count[i] = len(ni.pods)
                max_pods[i] = ni.allocatable.allowed_pod_number \
                    or 1_000_000

        self._for_node_shards(fill_node_rows)
        sv_attached = None
        sv_keys = None
        if self._attach_col:
            self._collect_shared_volume_slots()
            if self._sv_slots:
                # pad the slot axis (power-of-2, min 8): new shared PVs
                # within the pad reuse the compiled executable
                sv_pad = max(8, 1 << (len(self._sv_slots) - 1).bit_length())
                self._sv_pad = sv_pad
                sv_attached = np.zeros((sv_pad, n_pad), dtype=np.int32)
            self._fill_attach_node_columns(allocatable, requested,
                                           sv_attached)
            if sv_attached is not None:
                import zlib

                keys = np.zeros(sv_attached.shape[0], dtype=np.int64)
                for i, (d, v) in enumerate(self._sv_keys):
                    keys[i] = zlib.crc32(f"{d}\x00{v}".encode())
                sv_keys = keys

        cluster = EncodedCluster(
            node_names=[ni.node.name for ni in nis],
            num_real_nodes=n_real,
            resource_names=resource_names,
            allocatable=allocatable,
            requested=requested,
            nonzero_requested=nonzero_req,
            pod_count=pod_count,
            max_pods=max_pods,
            sv_attached=sv_attached,
            sv_keys=sv_keys,
        )

        batch = self._encode_pods(cluster, pods, pod_infos, n_pad, pad_pods)
        return cluster, batch

    def _collect_resource_names(self, pod_infos: List[PodInfo]) -> List[str]:
        names = [CPU, MEMORY, EPHEMERAL_STORAGE]
        seen = set(names) | {PODS}
        for ni in self.node_infos:
            for name in ni.allocatable.scalar_resources:
                if name not in seen:
                    seen.add(name)
                    names.append(name)
        for pi in pod_infos:
            for name in pi.resource_request.scalar_resources:
                if name not in seen:
                    seen.add(name)
                    names.append(name)
        # CSI attach-limit columns, appended LAST so the cpu/mem column
        # indices the scorers rely on stay 0/1. ALL limited drivers get
        # a column (not just this batch's): a later incremental batch
        # carrying a limited driver then always fits the space.
        self._attach_drivers = self._attach_limit_drivers()
        self._attach_col = {}
        self._attached_volumes = set()  # repopulated by the node fill
        for d in self._attach_drivers:
            self._attach_col[d] = len(names)
            names.append(ATTACH_COL_PREFIX + d)
        return names

    def _attach_limit_drivers(self) -> List[str]:
        """CSI drivers with a published CSINode attach limit anywhere in
        the cluster. Frozen per epoch — CSINode add/update events bump
        the cache's external-mutation counter, invalidating the session
        before the set can drift."""
        if self._client is None:
            return []
        drivers = set()
        for cn in self._client.list_csi_nodes():
            for d in cn.drivers:
                if d.allocatable_count is not None:
                    drivers.add(d.name)
        return sorted(drivers)

    def _pod_attach(self, pod: Pod) -> frozenset:
        """Memoized (driver, volume-key) attach set for a pod (the
        node-side in-use scan touches every existing pod)."""
        from kubernetes_tpu.scheduler.framework.plugins.node_volume_limits import (
            pod_csi_volumes,
        )

        if not pod.spec.volumes:
            return frozenset()
        key = pod.uid or pod.full_name()
        got = self._pod_attach_cache.get(key)
        if got is None:
            got = frozenset(pod_csi_volumes(self._client, pod))
            self._pod_attach_cache[key] = got
        return got

    def _volume_is_shared(self, driver: str, vol_key: str) -> bool:
        """Is (driver, vol_key) a SHARED volume? Memoized per epoch
        (PV/PVC churn rebuilds); the predicate itself is the module's
        single shared-volume rule (``pv_is_shared``), shared with
        ``is_host_only`` so partitioner and encoder can never
        disagree."""
        got = self._vol_shared_cache.get(vol_key)
        if got is None:
            pv = self._client.get_pv(vol_key)
            got = pv is not None and pv_is_shared(self._client, pv)
            self._vol_shared_cache[vol_key] = got
        return got

    def _collect_shared_volume_slots(self) -> None:
        """Per-epoch slots for every SHARED CSI volume the cluster could
        schedule against (the per-claim attach planes' index space).
        Enumerated from PVs so slots are stable for the whole epoch —
        PV/PVC churn bumps the cache mutation seq and rebuilds."""
        self._sv_slots = {}
        self._sv_keys = []
        self._vol_shared_cache = {}
        for pv in self._client.list_pvs():
            driver = getattr(pv, "csi_driver", "")
            if not driver or driver not in self._attach_col:
                continue
            if pv_is_shared(self._client, pv):
                key = (driver, pv.name)
                if key not in self._sv_slots:
                    self._sv_slots[key] = len(self._sv_keys)
                    self._sv_keys.append(key)

    def _fill_attach_node_columns(self, allocatable: np.ndarray,
                                  requested: np.ndarray,
                                  sv_attached=None) -> None:
        """Per-node attach budgets: allocatable = the CSINode limit (or
        the NO_LIMIT sentinel), requested = distinct in-use volumes,
        CLAMPED to the limit — an already-over-limit node must reject
        pods that attach (requested + req > limit) while still admitting
        volume-free pods (requested + 0 <= limit), matching csi.go's
        ``len(in_use | wanted) > limit`` which only fires for pods with
        wanted volumes."""
        for i, ni in enumerate(self.node_infos):
            in_use: Dict[str, set] = {}
            for pi in ni.pods:
                for d, v in self._pod_attach(pi.pod):
                    in_use.setdefault(d, set()).add(v)
                    if d in self._attach_col:
                        self._attached_volumes.add((d, v))
            cn = self._client.get_csi_node(ni.node.name)
            limits: Dict[str, int] = {}
            if cn is not None:
                for drv in cn.drivers:
                    if drv.allocatable_count is not None:
                        limits[drv.name] = drv.allocatable_count
            for dname, col in self._attach_col.items():
                limit = limits.get(dname, NO_LIMIT)
                allocatable[i, col] = limit
                requested[i, col] = min(len(in_use.get(dname, ())), limit)
            if sv_attached is not None:
                for d, vols in in_use.items():
                    # an already-OVER-limit node keeps its attached
                    # bits CLEAR: the shared pod's demand then reads 1
                    # and the clamped column rejects it — matching the
                    # host filter, which refuses ANY csi-volume pod on
                    # an over-limit node (csi.go attached+new > limit);
                    # a demand-0 pass-through would diverge
                    if len(vols) > limits.get(d, NO_LIMIT):
                        continue
                    for v in vols:
                        slot = self._sv_slots.get((d, v))
                        if slot is not None:
                            sv_attached[slot, i] = 1

    # ------------------------------------------------------------------
    def _encode_pods(self, cluster: EncodedCluster, pods: List[Pod],
                     pod_infos: List[PodInfo], n_pad: int,
                     pad_pods: int) -> EncodedBatch:
        b_real = len(pods)

        # -------- topology keys: collect from constraints and terms
        topo_keys: List[str] = []
        key_index: Dict[str, int] = {}

        def key_idx(key: str) -> int:
            if key not in key_index:
                key_index[key] = len(topo_keys)
                topo_keys.append(key)
            return key_index[key]

        # tracked spread constraints (dedup); the per-pod membership masks
        # are filled later by encode_pods_only via the same indices
        constraints: List[_TrackedConstraint] = []
        con_index: Dict[tuple, int] = {}
        for pod in pods:
            for c in pod.spec.topology_spread_constraints:
                if not c.topology_key:
                    continue
                sel = labelslib.selector_from_label_selector(c.label_selector)
                key = _constraint_key(pod, c, sel)
                if key not in con_index:
                    con_index[key] = len(constraints)
                    constraints.append(
                        _TrackedConstraint(
                            key_idx(c.topology_key), c.max_skew, sel,
                            pod.namespace,
                            c.when_unsatisfiable == "DoNotSchedule",
                        )
                    )

        # tracked terms: batch pods' required aff/anti + preferred, plus
        # existing pods' required anti-affinity (owners)
        terms: List[_TrackedTerm] = []
        term_index: Dict[tuple, int] = {}

        def term_for(t) -> int:
            key = _term_key(t)
            if key not in term_index:
                term_index[key] = len(terms)
                terms.append(
                    _TrackedTerm(key_idx(t.topology_key), t.selector, t.namespaces)
                )
            return term_index[key]

        for pi in pod_infos:
            for t in pi.required_affinity_terms:
                term_for(t)
            for t in pi.required_anti_affinity_terms:
                term_for(t)
            for wt in pi.preferred_affinity_terms:
                term_for(wt.term)
            for wt in pi.preferred_anti_affinity_terms:
                term_for(wt.term)

        existing_anti_terms: List[Tuple[int, object]] = []  # (term idx, owner node)
        for ni in self.snapshot.have_pods_with_required_anti_affinity_list():
            if ni.node is None:
                continue
            for existing in ni.pods_with_required_anti_affinity:
                for t in existing.required_anti_affinity_terms:
                    existing_anti_terms.append((term_for(t), ni.node))

        # -------- topology value coding (shared value space, padded)
        k = len(topo_keys)
        value_codes: List[Dict[str, int]] = [dict() for _ in range(k)]
        topo_codes = np.full((n_pad, max(k, 1)), -1, dtype=np.int32)
        for i, ni in enumerate(self.node_infos):
            labels = ni.node.metadata.labels
            for ki, key in enumerate(topo_keys):
                if key in labels:
                    vc = value_codes[ki]
                    v = labels[key]
                    if v not in vc:
                        vc[v] = len(vc)
                    topo_codes[i, ki] = vc[v]
        num_values = max((len(vc) for vc in value_codes), default=0)
        num_values = max(num_values, 1)
        cluster.topo_keys = topo_keys
        cluster.topo_codes = topo_codes
        cluster.topo_num_values = np.array(
            [len(vc) for vc in value_codes] or [0], dtype=np.int32
        )
        # missing key -> sentinel column V
        topo_codes[topo_codes < 0] = num_values

        # -------- static profiles
        profiles: Dict[tuple, int] = {}
        profile_pods: List[Pod] = []
        for pod in pods:
            key = self._static_profile_key(pod)
            if key not in profiles:
                profiles[key] = len(profile_pods)
                profile_pods.append(pod)
        u = max(len(profile_pods), 1)
        static_masks = np.zeros((u, n_pad), dtype=bool)
        affinity_masks = np.zeros((u, n_pad), dtype=bool)
        static_scores = np.zeros((u, n_pad), dtype=np.float32)
        if self._sharding_active():
            # the O(U × N) plugin sweep is the rebuild's dominant host
            # cost: one task per (profile, node shard), each emitting
            # the columns of exactly one device shard. The per-POD
            # volume context (host-only verdict, plugin construction,
            # vb.pre_filter's client resolution) is hoisted out and
            # computed once per profile — only the per-NODE loops fan
            # out to the workers.
            contexts = [self._volume_ctx(pod) for pod in profile_pods]
            self._run_encode_workers([
                partial(self._compute_static, pod, static_masks[ui],
                        affinity_masks[ui], static_scores[ui], sl,
                        contexts[ui])
                for ui, pod in enumerate(profile_pods)
                for sl in _node_slices(len(self.node_infos),
                                       self.node_shards)
            ])
        else:
            for ui, pod in enumerate(profile_pods):
                self._compute_static(pod, static_masks[ui],
                                     affinity_masks[ui],
                                     static_scores[ui])

        # retain the encoding space, then fill the pod-side arrays with
        # THE SAME code the incremental path uses — a single
        # implementation cannot diverge between the two paths
        self._resource_names = cluster.resource_names
        self._key_index = key_index
        self._con_index = con_index
        self._constraints = constraints
        self._term_index = term_index
        self._terms = terms
        self._profiles = profiles
        self._num_values = num_values
        self._con_match_idx = _build_match_index(constraints)
        self._term_match_idx = _build_match_index(terms)
        pb = self.encode_pods_only(pods, pad_pods)
        if pb is None:  # cannot happen: every pod was just registered
            raise RuntimeError("pod-side encode failed against a space "
                               "built from the same pods")
        b_pad = pb.requests.shape[0]

        # -------- cluster-side spread constraint arrays
        sc = max(len(constraints), 1)
        sc_key_idx = np.zeros(sc, dtype=np.int32)
        sc_max_skew = np.ones(sc, dtype=np.int32)
        sc_hard = np.zeros(sc, dtype=bool)
        sc_counts = np.zeros((sc, num_values + 1), dtype=np.int32)
        sc_domain = np.zeros((u, sc, num_values + 1), dtype=bool)
        for ci, con in enumerate(constraints):
            sc_key_idx[ci] = con.key_idx
            sc_max_skew[ci] = con.max_skew
            sc_hard[ci] = con.hard
            # existing matching pods per domain value
            for i, ni in enumerate(self.node_infos):
                code = topo_codes[i, con.key_idx]
                if code >= num_values:
                    continue
                count = sum(
                    1
                    for pi in ni.pods
                    if pi.pod.metadata.deletion_timestamp is None
                    and con.matches(pi.pod)
                )
                sc_counts[ci, code] += count
            # eligible domains per profile
            for ui in range(len(profile_pods)):
                for i in range(len(self.node_infos)):
                    if affinity_masks[ui, i]:
                        code = topo_codes[i, con.key_idx]
                        if code < num_values:
                            sc_domain[ui, ci, code] = True

        # -------- cluster-side term arrays
        t_n = max(len(terms), 1)
        term_key_idx = np.zeros(t_n, dtype=np.int32)
        term_counts = np.zeros((t_n, num_values + 1), dtype=np.int32)
        term_owners = np.zeros((t_n, num_values + 1), dtype=np.int32)
        for ti, term in enumerate(terms):
            term_key_idx[ti] = term.key_idx
            for i, ni in enumerate(self.node_infos):
                code = topo_codes[i, term.key_idx]
                if code >= num_values:
                    continue
                count = sum(1 for pi in ni.pods if term.matches(pi.pod))
                term_counts[ti, code] += count
        node_idx = {ni.node.name: i for i, ni in enumerate(self.node_infos)}
        for ti, owner_node in existing_anti_terms:
            i = node_idx[owner_node.name]
            code = topo_codes[i, terms[ti].key_idx]
            if code < num_values:
                term_owners[ti, code] += 1

        return EncodedBatch(
            pods=pods,
            num_real_pods=b_real,
            requests=pb.requests,
            nonzero_requests=pb.nonzero_requests,
            profile_idx=pb.profile_idx,
            static_masks=static_masks,
            affinity_masks=affinity_masks,
            static_scores=static_scores,
            inexpressible=pb.inexpressible,
            sc_key_idx=sc_key_idx,
            sc_max_skew=sc_max_skew,
            sc_hard=sc_hard,
            sc_counts=sc_counts,
            sc_domain=sc_domain,
            pod_sc=pb.pod_sc,
            pod_sc_match=pb.pod_sc_match,
            term_key_idx=term_key_idx,
            term_counts=term_counts,
            term_owners=term_owners,
            match_by=pb.match_by,
            own_aff=pb.own_aff,
            own_anti=pb.own_anti,
            pref_weight=pb.pref_weight,
            pod_sv=pb.pod_sv,
            num_values=num_values,
        )

    # ------------------------------------------------------------------
    def encode_pods_only(self, pods: List[Pod],
                         pad_pods: int) -> Optional[EncodedPodBatch]:
        """Encode ONLY the pod-side arrays of ``pods`` against the space
        retained by the last full ``encode``. Returns None when any pod
        does not fit that space (new scalar resource, untracked topology
        constraint/term, unseen static profile) — the caller then rebuilds
        the session with a full encode."""
        if self._resource_names is None:
            return None
        b_real = len(pods)
        # ONE compiled shape for every batch up to pad_pods (the sidecar's
        # max_batch): a pow2 bucket between b_real and pad_pods would
        # recompile mid-run on a partially-filled drain. Rounded to 8 for
        # the pallas kernel's SMEM sublane tiling.
        b_pad = _round_up(
            pad_pods if b_real <= pad_pods
            else 1 << (b_real - 1).bit_length(), 8
        )
        resource_names = self._resource_names
        known_resources = set(resource_names)
        constraints = self._constraints
        terms = self._terms
        r = len(resource_names)
        sc = max(len(constraints), 1)
        t_n = max(len(terms), 1)

        # pooled (delta-column) planes: zero only the previously-dirty
        # rows, then fill the new batch's — see _pod_plane_pool
        key = (b_pad, r, sc, t_n, self._sv_pad)
        bufs = self._pod_plane_pool.get(key)
        if bufs is None:
            bufs = {
                "requests": np.zeros((b_pad, r), dtype=np.int32),
                "nonzero_requests": np.zeros((b_pad, 2),
                                             dtype=np.int32),
                "pod_sc": np.zeros((b_pad, sc), dtype=bool),
                "pod_sc_match": np.zeros((b_pad, sc), dtype=bool),
                "match_by": np.zeros((b_pad, t_n), dtype=bool),
                "own_aff": np.zeros((b_pad, t_n), dtype=bool),
                "own_anti": np.zeros((b_pad, t_n), dtype=bool),
                "dirty": 0,
            }
            if self._sv_pad:
                # sentinel slot = the padded dim (never a real plane)
                bufs["pod_sv"] = np.full((b_pad, 2), (self._sv_pad, 0),
                                         dtype=np.int32)
            self._pod_plane_pool[key] = bufs
        else:
            dirty = bufs["dirty"]
            for name in ("requests", "nonzero_requests", "pod_sc",
                         "pod_sc_match", "match_by", "own_aff",
                         "own_anti"):
                bufs[name][:dirty] = 0
            if self._sv_pad:
                bufs["pod_sv"][:dirty] = (self._sv_pad, 0)
        # rows filled below — recorded BEFORE the loop so an early
        # bail (pod outside the space → rebuild) still marks them
        bufs["dirty"] = b_real
        requests = bufs["requests"]
        nonzero_requests = bufs["nonzero_requests"]
        pod_sc = bufs["pod_sc"]
        pod_sc_match = bufs["pod_sc_match"]
        match_by = bufs["match_by"]
        own_aff = bufs["own_aff"]
        own_anti = bufs["own_anti"]
        pod_sv = bufs.get("pod_sv")
        # NOT pooled: retained by the sidecar's pending dict past this
        # call (profile_idx, inexpressible) or returned as a no-copy
        # view by pack_podin (pref_weight)
        profile_idx = np.zeros(b_pad, dtype=np.int32)
        inexpressible = np.zeros(b_pad, dtype=bool)
        pref_weight = np.zeros((b_pad, t_n), dtype=np.float32)

        for bi, pod in enumerate(pods):
            pi = PodInfo.of(pod)
            if any(
                name not in known_resources
                for name in pi.resource_request.scalar_resources
            ):
                return None
            ui = self._profiles.get(self._static_profile_key(pod))
            if ui is None:
                return None
            profile_idx[bi] = ui
            requests[bi] = _resource_row(pi.resource_request, resource_names)
            nonzero_requests[bi] = (
                pi.non_zero_request.milli_cpu,
                _kib(pi.non_zero_request.memory),
            )
            inexpressible[bi] = self._is_inexpressible(pod)
            if self._attach_col and not inexpressible[bi] and \
                    pod.spec.volumes:
                relevant = {
                    (d, v) for d, v in self._pod_attach(pod)
                    if d in self._attach_col
                }
                shared = {p for p in relevant if p in self._sv_slots}
                unslotted_shared = {
                    (d, v) for d, v in relevant - shared
                    if self._volume_is_shared(d, v)
                }
                if unslotted_shared:
                    # a shared volume that post-dates this epoch's slot
                    # enumeration: rebuild so it gets a plane (the PV
                    # write that created it bumped the mutation seq)
                    return None
                relevant -= shared
                if len(shared) > 1:
                    # one conditional-demand plane per pod per step; a
                    # multi-shared-volume pod keeps the host path
                    inexpressible[bi] = True
                elif shared:
                    d, v = next(iter(shared))
                    pod_sv[bi] = (self._sv_slots[(d, v)],
                                  self._attach_col[d])
                if inexpressible[bi]:
                    pass
                elif relevant & self._attached_volumes:
                    # NON-shared volume reused by an existing or
                    # earlier-batch pod: serial path for exact
                    # set-union semantics
                    inexpressible[bi] = True
                else:
                    self._attached_volumes |= relevant
                    for d, _v in relevant:
                        requests[bi, self._attach_col[d]] += 1

            for c in pod.spec.topology_spread_constraints:
                if not c.topology_key:
                    continue
                sel = labelslib.selector_from_label_selector(c.label_selector)
                ci = self._con_index.get(_constraint_key(pod, c, sel))
                if ci is None:
                    return None
                pod_sc[bi, ci] = True
            simple_cons, complex_cons = self._con_match_idx
            labels = pod.metadata.labels or {}
            for kv in labels.items():
                for ci, con in simple_cons.get(kv, ()):
                    if pod.namespace == con.namespace:
                        pod_sc_match[bi, ci] = True
            for ci, con in complex_cons:
                pod_sc_match[bi, ci] = con.matches(pod)

            def tracked(t) -> Optional[int]:
                return self._term_index.get(_term_key(t))

            for t in pi.required_affinity_terms:
                ti = tracked(t)
                if ti is None:
                    return None
                own_aff[bi, ti] = True
            for t in pi.required_anti_affinity_terms:
                ti = tracked(t)
                if ti is None:
                    return None
                own_anti[bi, ti] = True
            for wt in pi.preferred_affinity_terms:
                ti = tracked(wt.term)
                if ti is None:
                    return None
                pref_weight[bi, ti] += float(wt.weight)
            for wt in pi.preferred_anti_affinity_terms:
                ti = tracked(wt.term)
                if ti is None:
                    return None
                pref_weight[bi, ti] -= float(wt.weight)
            simple_terms, complex_terms = self._term_match_idx
            for kv in labels.items():
                for ti, term in simple_terms.get(kv, ()):
                    if pod.namespace in term.namespaces:
                        match_by[bi, ti] = True
            for ti, term in complex_terms:
                match_by[bi, ti] = term.matches(pod)

        return EncodedPodBatch(
            pods=pods,
            num_real_pods=b_real,
            requests=requests,
            nonzero_requests=nonzero_requests,
            profile_idx=profile_idx,
            inexpressible=inexpressible,
            pod_sc=pod_sc,
            pod_sc_match=pod_sc_match,
            match_by=match_by,
            own_aff=own_aff,
            own_anti=own_anti,
            pref_weight=pref_weight,
            pod_sv=pod_sv,
        )

    # ------------------------------------------------------------------
    def _static_profile_key(self, pod: Pod) -> tuple:
        spec = pod.spec
        aff_repr = ""
        if spec.affinity is not None and spec.affinity.node_affinity is not None:
            na = spec.affinity.node_affinity
            req = na.required_during_scheduling_ignored_during_execution
            aff_repr = repr(
                [
                    [(e.key, e.operator, tuple(e.values)) for e in t.match_expressions]
                    + [("f:" + e.key, e.operator, tuple(e.values)) for e in t.match_fields]
                    for t in (req.node_selector_terms if req else [])
                ]
            ) + repr(
                [
                    (p.weight,
                     [(e.key, e.operator, tuple(e.values))
                      for e in p.preference.match_expressions])
                    for p in na.preferred_during_scheduling_ignored_during_execution
                ]
            )
        return (
            spec.node_name,
            tuple(sorted(spec.node_selector.items())),
            aff_repr,
            tuple(
                (t.key, t.operator, t.value, t.effect) for t in spec.tolerations
            ),
            tuple(sorted(c.image for c in spec.containers)),
            self._volume_profile_identity(pod),
            # mesh-block component: two gangs anchor to different mesh
            # coordinates, so their static score columns must differ;
            # () for every unlabeled pod — existing keys unchanged
            mesh_locality.profile_component(pod),
        )

    def _volume_profile_identity(self, pod: Pod) -> tuple:
        """Volume component of the static profile key: two pods share a
        profile only when their PVC-backed volumes impose the SAME
        node feasibility — i.e. the multiset of (PV node-affinity, PV
        zone labels) matches. Distinct PVs with no affinity/zone all
        reduce to the same identity, so the 1-claim-per-pod bench
        workloads collapse to one profile."""
        if self._client is None:
            return ()
        from kubernetes_tpu.scheduler.framework.plugins.volume_zone import (
            TOPOLOGY_LABELS,
        )

        ident = []
        for v in pod.spec.volumes:
            if not v.persistent_volume_claim:
                continue
            pvc = self._client.get_pvc(pod.namespace, v.persistent_volume_claim)
            if pvc is None:
                ident.append(("missing", v.persistent_volume_claim))
                continue
            if not pvc.volume_name:
                if wfc_class_batchable(self._client,
                                       pvc.storage_class_name,
                                       self._wfc_cache):
                    # node-independent pool: feasibility is a property
                    # of the CLASS, so every such pod shares a profile
                    # (a per-claim identity would explode U to one
                    # profile per pod)
                    ident.append(("wfc", pvc.storage_class_name))
                else:
                    # host-only shapes; identity only needs stability
                    ident.append(("unbound", v.persistent_volume_claim))
                continue
            pv = self._client.get_pv(pvc.volume_name)
            if pv is None:
                ident.append(("missing-pv", pvc.volume_name))
                continue
            zones = tuple(
                (lb, pv.metadata.labels[lb])
                for lb in TOPOLOGY_LABELS
                if lb in pv.metadata.labels
            )
            ident.append(("pv", repr(pv.node_affinity), zones))
        return tuple(sorted(ident))

    # sentinel: "compute the volume context yourself" (the serial path);
    # the sharded sweep precomputes one context per profile and shares
    # it across that profile's shard tasks
    _VOL_CTX_UNSET = object()

    def _volume_ctx(self, pod: Pod):
        """Per-POD half of the volume-feasibility work: the host-only
        verdict, plugin construction and ``vb.pre_filter``'s client
        resolution — node-independent, so the sharded sweep computes it
        ONCE per profile instead of once per (profile, shard). Returns
        None when the pod imposes no expressible volume constraint,
        else ``(vb, vz, state, prefilter_failed)``; the CycleState is
        written only by pre_filter here and read-only in the per-node
        filters, so sharing it across shard workers is safe."""
        if not (
            self._client is not None
            and any(v.persistent_volume_claim for v in pod.spec.volumes)
            and not is_host_only(pod, self._client, self._wfc_cache)
        ):
            return None
        from kubernetes_tpu.scheduler.framework.plugins.volume_binding import (  # noqa: E501
            VolumeBinding,
        )
        from kubernetes_tpu.scheduler.framework.plugins.volume_zone import (
            VolumeZone,
        )

        handle = _ClientHandle(self._client)
        vb = VolumeBinding(handle)
        vz = VolumeZone(handle)
        state = CycleState()
        failed = vb.pre_filter(state, pod) is not None
        return (vb, vz, state, failed)

    def _compute_static(self, pod: Pod, mask: np.ndarray,
                        affinity_mask: np.ndarray,
                        scores: np.ndarray,
                        node_range: Optional[slice] = None,
                        vol_ctx=_VOL_CTX_UNSET) -> None:
        """Evaluate node-static predicates/scores with the real host
        plugins so the device path is differentially exact.
        ``node_range`` restricts the sweep to one node-column shard
        (the sharded encode stage) — every plugin here is per-node
        stateless, so a sharded sweep is bit-identical to the serial
        one. ``vol_ctx`` is the precomputed per-pod volume context
        (``_volume_ctx``); left unset, it is computed here (the serial
        path's one call per profile)."""
        if node_range is None:
            node_range = slice(0, len(self.node_infos))
        state = CycleState()
        # mesh-adjacency scorer, hoisted per profile: the anchor/grid
        # extent is a whole-cluster property, so it is computed from
        # the FULL node list even when this task sweeps one shard —
        # the sharded sweep stays bit-identical to the serial one.
        # Label-gated BEFORE materializing the node list: unlabeled
        # profiles (every existing workload) must not pay an O(N)
        # allocation per (profile, shard) task
        mesh_fn = None
        if mesh_locality.enabled() and mesh_locality.mesh_block(pod):
            mesh_fn = mesh_locality.profile_scorer(
                pod, [n.node for n in self.node_infos])
        for i in range(node_range.start, node_range.stop):
            ni = self.node_infos[i]
            node = ni.node
            ok_affinity = pod_matches_node_selector_and_affinity(pod, node)
            affinity_mask[i] = ok_affinity
            ok = ok_affinity
            if ok and pod.spec.node_name and pod.spec.node_name != node.name:
                ok = False
            if ok and self._unsched_plugin.filter(state, pod, ni) is not None:
                ok = False
            if ok and self._taint_plugin.filter(state, pod, ni) is not None:
                ok = False
            mask[i] = ok
            if ok:
                scores[i] = self._static_score(pod, ni)
                if mesh_fn is not None:
                    scores[i] += mesh_fn(node)
        if vol_ctx is self._VOL_CTX_UNSET:
            vol_ctx = self._volume_ctx(pod)
        if vol_ctx is not None:
            self._apply_volume_feasibility(pod, mask, node_range,
                                           vol_ctx)

    def _apply_volume_feasibility(self, pod: Pod, mask: np.ndarray,
                                  node_range: Optional[slice],
                                  vol_ctx) -> None:
        """Fold PV node-affinity + zone feasibility into the static mask
        using the REAL host plugins (differential exactness, like the
        other static predicates). Only reached for expressible pods —
        all claims bound — so VolumeBinding's Filter is the pure
        bound-claim affinity check and Reserve/PreBind stay no-ops.

        Note on preemption semantics: the reference reports volume
        conflicts as plain Unschedulable, keeping such nodes preemption
        *candidates*; folding them into the static mask marks them
        UnschedulableAndUnresolvable, pruning them earlier. Outcome-
        equivalent — evicting pods never fixes a PV affinity/zone
        conflict, so the reference's dry-run re-filter would reject the
        node anyway."""
        if node_range is None:
            node_range = slice(0, len(self.node_infos))
        vb, vz, state, prefilter_failed = vol_ctx
        if prefilter_failed:
            # each shard worker clears ITS columns; the verdict is
            # per-pod, so every shard reaches the same branch
            mask[node_range] = False
            return
        for i in range(node_range.start, node_range.stop):
            ni = self.node_infos[i]
            if not mask[i]:
                continue
            if (
                vb.filter(state, pod, ni) is not None
                or vz.filter(state, pod, ni) is not None
            ):
                mask[i] = False

    @staticmethod
    def _static_score(pod: Pod, ni) -> float:
        """Static score contributions (preferred node affinity weights;
        image locality). Dynamic scores live on device."""
        from kubernetes_tpu.scheduler.framework.plugins.helpers import (
            node_selector_term_matches,
        )

        score = 0.0
        aff = pod.spec.affinity
        if aff is not None and aff.node_affinity is not None:
            for term in aff.node_affinity.preferred_during_scheduling_ignored_during_execution:
                if term.weight and node_selector_term_matches(term.preference, ni.node):
                    score += term.weight
        for c in pod.spec.containers:
            state = ni.image_states.get(c.image)
            if state is not None:
                score += min(state.size / (1024 * 1024 * 1024), 1.0)  # ≤1 pt/GiB
        return score

    def _is_inexpressible(self, pod: Pod) -> bool:
        return is_host_only(pod, self._client, self._wfc_cache)


def wfc_class_batchable(client, sc_name: str, cache=None) -> bool:
    """True when an UNBOUND claim of this storage class is expressible
    on the batch path:

    - WaitForFirstConsumer binding (Immediate unbound claims are
      unschedulable until the PV controller acts — host semantics);
    - the provisioner has no published CSINode attach limit anywhere
      (otherwise the claim consumes attach budget the columns must
      track per claim);
    - every candidate PV (Available, unclaimed, same class) is free of
      node affinity — the match result is then identical on every
      node, so scheduling carries NO volume constraint and the actual
      PV assignment can happen at commit time.

    O(PVs + CSINodes) per class; callers scanning many pods pass a
    per-drain ``cache`` dict so one drain pays one scan per class."""
    if not sc_name:
        return False
    if cache is not None and ("wfc", sc_name) in cache:
        return cache[("wfc", sc_name)]
    verdict = False
    sc = client.get_storage_class(sc_name)
    if sc is not None and \
            sc.volume_binding_mode == "WaitForFirstConsumer":
        limited = any(
            d.name == sc.provisioner and d.allocatable_count is not None
            for cn in client.list_csi_nodes() for d in cn.drivers
        )
        if not limited:
            verdict = all(
                pv.node_affinity is None
                for pv in client.list_pvs()
                if pv.phase == "Available" and pv.claim_ref is None
                and pv.storage_class_name == sc_name
            )
    if cache is not None:
        cache[("wfc", sc_name)] = verdict
    return verdict


def pv_is_shared(client, pv) -> bool:
    """THE shared-volume predicate (single rule for is_host_only, slot
    enumeration, and the incremental encoder): a PV is shared when it —
    or the claim its ``claim_ref`` names — carries a RWX/ROX access
    mode. A one-sided binding (PVC shared, PV silent with no
    claim_ref) is deliberately NOT shared under this rule everywhere
    at once: such pods stay on the additive/serial path consistently
    instead of flapping between classifiers."""
    if any(m in SHARED_ACCESS_MODES for m in pv.access_modes):
        return True
    if pv.claim_ref:
        ns, _, nm = pv.claim_ref.partition("/")
        pvc = client.get_pvc(ns, nm)
        return pvc is not None and any(
            m in SHARED_ACCESS_MODES for m in pvc.access_modes)
    return False


def is_host_only(pod: Pod, client=None, cache=None) -> bool:
    """Pods needing host-only machinery take the serial path — the single
    source of truth shared by the encoder and the sidecar's partitioner.

    Host-only: inline cloud-disk volumes (``VolumeRestrictions``'
    node-pod conflict scan and the in-tree attach limits are dynamic
    host-side checks), host ports (``UsedPorts`` conflict tracking), and
    PVC volumes that are NOT plainly bound — with one carve-out: an
    unbound WaitForFirstConsumer claim whose class is attach-irrelevant
    and whose candidate PV pool is NODE-INDEPENDENT (no candidate
    carries node affinity) imposes no per-node constraint at all, so it
    batches; the sidecar assigns an actual PV from the pool at COMMIT
    time (the Reserve/PreBind moment) and falls back to the serial path
    if the pool ran dry with no provisioner. Other unbound claims need
    the stateful per-node ``VolumeBinding`` match machinery, and
    CSI-attached shared (RWX/ROX) claims would double-count in the
    attach-column model (a shared claim with no CSI driver consumes no
    attach budget, so it batches). A bound claim with a live PV is
    otherwise fully expressible:
    feasibility is the PV's static node affinity/zone plus the CSI
    attach-limit resource columns. Without a ``client`` every PVC pod is
    conservatively host-only (the pre-round-3 contract)."""
    for v in pod.spec.volumes:
        if (
            v.gce_persistent_disk or v.aws_elastic_block_store
            or v.azure_disk or v.rbd or v.iscsi
        ):
            return True
    if any(p.host_port > 0 for c in pod.spec.containers for p in c.ports):
        return True
    shared_csi = 0
    for v in pod.spec.volumes:
        if not v.persistent_volume_claim:
            continue
        if client is None:
            return True
        pvc = client.get_pvc(pod.namespace, v.persistent_volume_claim)
        if pvc is None:
            return True
        if not pvc.volume_name:
            if not wfc_class_batchable(client, pvc.storage_class_name,
                                       cache):
                return True
            continue
        pv = client.get_pv(pvc.volume_name)
        if pv is None:
            return True
        if getattr(pv, "csi_driver", "") and pv_is_shared(client, pv):
            # CSI-attached shared volumes batch via the per-volume
            # attach planes (conditional per-node demand carried in
            # solver state — csi.go's len(in_use | wanted) set
            # semantics). One plane reference per pod per step: a pod
            # with SEVERAL shared CSI volumes keeps the host path.
            shared_csi += 1
            if shared_csi > 1:
                return True
    return False


class _ClientHandle:
    """Minimal framework-handle shim for running the host volume plugins
    inside the encoder (they only touch ``handle.client``)."""

    __slots__ = ("client",)

    def __init__(self, client):
        self.client = client
