"""Fluent builders for test pods/nodes.

Same role as the reference's ``pkg/scheduler/testing/wrappers.go``
(``MakePod():140``, ``MakeNode():401``): table-driven tests construct
objects with chained calls instead of nested literals.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from kubernetes_tpu.api.labels import LabelSelector, Requirement
from kubernetes_tpu.api.resource import parse_quantity
from kubernetes_tpu.api.types import (
    Affinity,
    Container,
    ContainerPort,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    ObjectMeta,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodSpec,
    PodStatus,
    PreferredSchedulingTerm,
    ResourceRequirements,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    Volume,
    WeightedPodAffinityTerm,
)


class PodWrapper:
    def __init__(self):
        self.pod = Pod()

    def obj(self) -> Pod:
        return self.pod

    def name(self, n: str) -> "PodWrapper":
        self.pod.metadata.name = n
        return self

    def namespace(self, ns: str) -> "PodWrapper":
        self.pod.metadata.namespace = ns
        return self

    def uid(self, u: str) -> "PodWrapper":
        self.pod.metadata.uid = u
        return self

    def label(self, k: str, v: str) -> "PodWrapper":
        self.pod.metadata.labels[k] = v
        return self

    def labels(self, m: Dict[str, str]) -> "PodWrapper":
        self.pod.metadata.labels.update(m)
        return self

    def container(self, image: str = "image", name: str = "") -> "PodWrapper":
        self.pod.spec.containers.append(
            Container(name=name or f"c{len(self.pod.spec.containers)}", image=image)
        )
        return self

    def req(self, resources: Dict[str, str]) -> "PodWrapper":
        """Add a container with the given resource requests."""
        self.pod.spec.containers.append(
            Container(
                name=f"c{len(self.pod.spec.containers)}",
                resources=ResourceRequirements(
                    requests={k: parse_quantity(v) for k, v in resources.items()}
                ),
            )
        )
        return self

    def init_req(self, resources: Dict[str, str]) -> "PodWrapper":
        self.pod.spec.init_containers.append(
            Container(
                name=f"init{len(self.pod.spec.init_containers)}",
                resources=ResourceRequirements(
                    requests={k: parse_quantity(v) for k, v in resources.items()}
                ),
            )
        )
        return self

    def overhead(self, resources: Dict[str, str]) -> "PodWrapper":
        self.pod.spec.overhead = {k: parse_quantity(v) for k, v in resources.items()}
        return self

    def host_port(self, port: int, protocol: str = "TCP", host_ip: str = "") -> "PodWrapper":
        if not self.pod.spec.containers:
            self.container()
        self.pod.spec.containers[-1].ports.append(
            ContainerPort(container_port=port, host_port=port, protocol=protocol, host_ip=host_ip)
        )
        return self

    def node(self, name: str) -> "PodWrapper":
        self.pod.spec.node_name = name
        return self

    def node_selector(self, m: Dict[str, str]) -> "PodWrapper":
        self.pod.spec.node_selector = dict(m)
        return self

    def priority(self, p: int) -> "PodWrapper":
        self.pod.spec.priority = p
        return self

    def scheduler_name(self, n: str) -> "PodWrapper":
        self.pod.spec.scheduler_name = n
        return self

    def phase(self, p: str) -> "PodWrapper":
        self.pod.status.phase = p
        return self

    def nominated_node_name(self, n: str) -> "PodWrapper":
        self.pod.status.nominated_node_name = n
        return self

    def terminating(self, ts: float = 1.0) -> "PodWrapper":
        self.pod.metadata.deletion_timestamp = ts
        return self

    def toleration(self, key: str, value: str = "", effect: str = "",
                   operator: str = "Equal") -> "PodWrapper":
        self.pod.spec.tolerations.append(
            Toleration(key=key, operator=operator, value=value, effect=effect)
        )
        return self

    def _affinity(self) -> Affinity:
        if self.pod.spec.affinity is None:
            self.pod.spec.affinity = Affinity()
        return self.pod.spec.affinity

    def node_affinity_in(self, key: str, vals: List[str]) -> "PodWrapper":
        aff = self._affinity()
        if aff.node_affinity is None:
            aff.node_affinity = NodeAffinity()
        if aff.node_affinity.required_during_scheduling_ignored_during_execution is None:
            aff.node_affinity.required_during_scheduling_ignored_during_execution = (
                NodeSelector([NodeSelectorTerm()])
            )
        aff.node_affinity.required_during_scheduling_ignored_during_execution.\
            node_selector_terms[0].match_expressions.append(
                NodeSelectorRequirement(key, "In", list(vals))
            )
        return self

    def preferred_node_affinity(self, weight: int, key: str, vals: List[str]) -> "PodWrapper":
        aff = self._affinity()
        if aff.node_affinity is None:
            aff.node_affinity = NodeAffinity()
        aff.node_affinity.preferred_during_scheduling_ignored_during_execution.append(
            PreferredSchedulingTerm(
                weight,
                NodeSelectorTerm(
                    match_expressions=[NodeSelectorRequirement(key, "In", list(vals))]
                ),
            )
        )
        return self

    def _pod_affinity_term(self, key: str, vals: List[str], topology_key: str) -> PodAffinityTerm:
        return PodAffinityTerm(
            label_selector=LabelSelector(
                match_expressions=[Requirement(key, "In", tuple(vals))]
            ),
            topology_key=topology_key,
        )

    def pod_affinity(self, key: str, vals: List[str], topology_key: str) -> "PodWrapper":
        aff = self._affinity()
        if aff.pod_affinity is None:
            aff.pod_affinity = PodAffinity()
        aff.pod_affinity.required_during_scheduling_ignored_during_execution.append(
            self._pod_affinity_term(key, vals, topology_key)
        )
        return self

    def pod_anti_affinity(self, key: str, vals: List[str], topology_key: str) -> "PodWrapper":
        aff = self._affinity()
        if aff.pod_anti_affinity is None:
            aff.pod_anti_affinity = PodAffinity()
        aff.pod_anti_affinity.required_during_scheduling_ignored_during_execution.append(
            self._pod_affinity_term(key, vals, topology_key)
        )
        return self

    def preferred_pod_affinity(self, weight: int, key: str, vals: List[str],
                               topology_key: str) -> "PodWrapper":
        aff = self._affinity()
        if aff.pod_affinity is None:
            aff.pod_affinity = PodAffinity()
        aff.pod_affinity.preferred_during_scheduling_ignored_during_execution.append(
            WeightedPodAffinityTerm(weight, self._pod_affinity_term(key, vals, topology_key))
        )
        return self

    def preferred_pod_anti_affinity(self, weight: int, key: str, vals: List[str],
                                    topology_key: str) -> "PodWrapper":
        aff = self._affinity()
        if aff.pod_anti_affinity is None:
            aff.pod_anti_affinity = PodAffinity()
        aff.pod_anti_affinity.preferred_during_scheduling_ignored_during_execution.append(
            WeightedPodAffinityTerm(weight, self._pod_affinity_term(key, vals, topology_key))
        )
        return self

    def spread_constraint(self, max_skew: int, topology_key: str,
                          when_unsatisfiable: str,
                          selector: Optional[Dict[str, str]] = None) -> "PodWrapper":
        self.pod.spec.topology_spread_constraints.append(
            TopologySpreadConstraint(
                max_skew=max_skew,
                topology_key=topology_key,
                when_unsatisfiable=when_unsatisfiable,
                label_selector=LabelSelector(match_labels=dict(selector or {})),
            )
        )
        return self

    def pvc(self, claim_name: str) -> "PodWrapper":
        self.pod.spec.volumes.append(
            Volume(name=f"vol{len(self.pod.spec.volumes)}",
                   persistent_volume_claim=claim_name)
        )
        return self

    def owner_reference(self, kind: str, name: str, uid: str = "",
                        controller: bool = True) -> "PodWrapper":
        self.pod.metadata.owner_references.append(
            {"kind": kind, "name": name, "uid": uid or f"{kind}-{name}",
             "controller": controller}
        )
        return self


class NodeWrapper:
    def __init__(self):
        self.node = Node()
        self.capacity({"pods": "110"})

    def obj(self) -> Node:
        return self.node

    def name(self, n: str) -> "NodeWrapper":
        self.node.metadata.name = n
        # kubernetes.io/hostname is implied by node identity in the reference;
        # tests rely on it for hostname topology.
        self.node.metadata.labels.setdefault("kubernetes.io/hostname", n)
        return self

    def label(self, k: str, v: str) -> "NodeWrapper":
        self.node.metadata.labels[k] = v
        return self

    def capacity(self, resources: Dict[str, str]) -> "NodeWrapper":
        for k, v in resources.items():
            q = parse_quantity(v)
            self.node.status.capacity[k] = q
            self.node.status.allocatable[k] = q
        return self

    def allocatable(self, resources: Dict[str, str]) -> "NodeWrapper":
        for k, v in resources.items():
            self.node.status.allocatable[k] = parse_quantity(v)
        return self

    def taint(self, key: str, value: str = "", effect: str = "NoSchedule") -> "NodeWrapper":
        self.node.spec.taints.append(Taint(key, value, effect))
        return self

    def unschedulable(self, v: bool = True) -> "NodeWrapper":
        self.node.spec.unschedulable = v
        return self

    def image(self, name: str, size_bytes: int) -> "NodeWrapper":
        from kubernetes_tpu.api.types import ContainerImage

        self.node.status.images.append(ContainerImage([name], size_bytes))
        return self


def MakePod() -> PodWrapper:
    return PodWrapper()


def MakeNode() -> NodeWrapper:
    return NodeWrapper()
