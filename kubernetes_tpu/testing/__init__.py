from kubernetes_tpu.testing.wrappers import MakeNode, MakePod, NodeWrapper, PodWrapper
