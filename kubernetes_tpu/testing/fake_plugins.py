"""Fake plugins for framework tests (reference
``pkg/scheduler/testing/fake_plugins.go``: TrueFilter/FalseFilter/
MatchFilter plus fake score/reserve/permit/bind plugins)."""

from __future__ import annotations

from kubernetes_tpu.scheduler.framework import interface as fw


class TrueFilter(fw.FilterPlugin):
    NAME = "TrueFilter"

    @staticmethod
    def factory(args, handle):
        return TrueFilter()

    def filter(self, state, pod, node_info):
        return None


class FalseFilter(fw.FilterPlugin):
    NAME = "FalseFilter"

    @staticmethod
    def factory(args, handle):
        return FalseFilter()

    def filter(self, state, pod, node_info):
        return fw.Status(fw.UNSCHEDULABLE, "injected filter failure")


class MatchFilter(fw.FilterPlugin):
    """Passes only when the node name equals the pod name."""

    NAME = "MatchFilter"

    @staticmethod
    def factory(args, handle):
        return MatchFilter()

    def filter(self, state, pod, node_info):
        if node_info.node is not None and node_info.node.name == pod.name:
            return None
        return fw.Status(fw.UNSCHEDULABLE, "node didn't match pod name")


class FakeScore(fw.ScorePlugin):
    NAME = "FakeScore"

    def __init__(self, score_fn):
        self.score_fn = score_fn

    def score(self, state, pod, node_name):
        return self.score_fn(pod, node_name), None


class RecordingReserve(fw.ReservePlugin):
    NAME = "RecordingReserve"

    def __init__(self, fail: bool = False):
        self.fail = fail
        self.reserved = []
        self.unreserved = []

    def reserve(self, state, pod, node_name):
        if self.fail:
            return fw.Status(fw.UNSCHEDULABLE, "reserve rejected")
        self.reserved.append((pod.name, node_name))
        return None

    def unreserve(self, state, pod, node_name):
        self.unreserved.append((pod.name, node_name))


class FakePermit(fw.PermitPlugin):
    NAME = "FakePermit"

    def __init__(self, code=fw.SUCCESS, timeout: float = 1.0):
        self.code = code
        self.timeout = timeout

    def permit(self, state, pod, node_name):
        if self.code == fw.SUCCESS:
            return None, 0.0
        return fw.Status(self.code, "fake permit"), self.timeout
