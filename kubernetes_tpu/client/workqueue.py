"""Work queues: FIFO-with-dedup, delaying, and rate-limited variants.

Behavioral equivalent of the reference's ``client-go/util/workqueue``
(``queue.go`` Type with dirty/processing sets, ``delaying_queue.go``,
``default_rate_limiters.go`` ItemExponentialFailureRateLimiter +
MaxOfRateLimiter), which every controller uses to decouple informer event
delivery from reconciliation: an item enqueued many times while being
processed is re-processed exactly once more.
"""

from __future__ import annotations

import heapq
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional


class WorkQueue:
    """FIFO queue with the dirty/processing dedup protocol.

    - ``add`` while the item is queued (dirty) is a no-op;
    - ``add`` while the item is being processed marks it dirty so ``done``
      re-queues it once;
    - ``get`` blocks until an item or shutdown.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._dirty: set = set()
        self._processing: set = set()
        self._shutting_down = False

    def add(self, item: Any) -> None:
        with self._cond:
            if self._shutting_down or item in self._dirty:
                return
            self._dirty.add(item)
            if item in self._processing:
                return
            self._queue.append(item)
            self._cond.notify()

    def get(self, timeout: Optional[float] = None) -> Optional[Any]:
        """Returns the next item, or None on shutdown/timeout. Callers must
        pair every successful get with ``done``."""
        with self._cond:
            deadline = None if timeout is None else time.monotonic() + timeout
            while not self._queue and not self._shutting_down:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                self._cond.wait(remaining)
            if not self._queue:
                return None
            item = self._queue.popleft()
            self._processing.add(item)
            self._dirty.discard(item)
            return item

    def done(self, item: Any) -> None:
        with self._cond:
            self._processing.discard(item)
            if item in self._dirty:
                self._queue.append(item)
                self._cond.notify()

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue)

    def shutdown(self) -> None:
        with self._cond:
            self._shutting_down = True
            self._cond.notify_all()

    @property
    def shutting_down(self) -> bool:
        with self._lock:
            return self._shutting_down


class DelayingQueue(WorkQueue):
    """WorkQueue + ``add_after``: deliver an item once its delay elapses
    (reference ``delaying_queue.go`` waitingLoop with a heap)."""

    def __init__(self, clock=None):
        super().__init__()
        from kubernetes_tpu.utils.clock import RealClock

        self._clock = clock or RealClock()
        self._waiting: List[tuple] = []  # (ready_time, seq, item)
        self._seq = 0
        self._waiting_cond = threading.Condition()
        self._waiter = threading.Thread(target=self._wait_loop, daemon=True,
                                        name="delaying-queue")
        self._waiter_started = False

    def add_after(self, item: Any, delay: float) -> None:
        if self.shutting_down:
            return
        if delay <= 0:
            self.add(item)
            return
        with self._waiting_cond:
            heapq.heappush(
                self._waiting, (self._clock.now() + delay, self._seq, item)
            )
            self._seq += 1
            if not self._waiter_started:
                self._waiter.start()
                self._waiter_started = True
            self._waiting_cond.notify()

    def _wait_loop(self) -> None:
        # sleep until the earliest waiting item is due (or a new item
        # arrives with an earlier deadline) — reference waitingLoop.
        while not self.shutting_down:
            with self._waiting_cond:
                now = self._clock.now()
                while self._waiting and self._waiting[0][0] <= now:
                    _, _, item = heapq.heappop(self._waiting)
                    self.add(item)
                if self._waiting:
                    # cap the wait so FakeClock-driven tests still progress
                    timeout = min(self._waiting[0][0] - self._clock.now(), 0.05)
                else:
                    timeout = 1.0
                self._waiting_cond.wait(timeout)

    def shutdown(self) -> None:
        super().shutdown()
        with self._waiting_cond:
            self._waiting_cond.notify_all()


class ItemExponentialFailureRateLimiter:
    """Per-item exponential backoff: base * 2^failures, capped."""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 1000.0):
        self._base = base_delay
        self._max = max_delay
        self._failures: Dict[Any, int] = {}
        self._lock = threading.Lock()

    def when(self, item: Any) -> float:
        with self._lock:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
            return min(self._base * (2 ** n), self._max)

    def forget(self, item: Any) -> None:
        with self._lock:
            self._failures.pop(item, None)

    def num_requeues(self, item: Any) -> int:
        with self._lock:
            return self._failures.get(item, 0)


class RateLimitingQueue(DelayingQueue):
    """DelayingQueue + a rate limiter (reference ``rate_limiting_queue.go``)."""

    def __init__(self, rate_limiter: Optional[ItemExponentialFailureRateLimiter] = None,
                 clock=None):
        super().__init__(clock=clock)
        self.rate_limiter = rate_limiter or ItemExponentialFailureRateLimiter()

    def add_rate_limited(self, item: Any) -> None:
        self.add_after(item, self.rate_limiter.when(item))

    def forget(self, item: Any) -> None:
        self.rate_limiter.forget(item)

    def num_requeues(self, item: Any) -> int:
        return self.rate_limiter.num_requeues(item)
