"""ClusterStore-shaped client over the REST API: the scheduler's remote
half.

The scheduler stack (Scheduler + TPUBatchScheduler + plugins + recorder)
talks to ONE seam: a ClusterStore-shaped ``client``. In-process runs
hand it the store; this module hands it the network — list/watch over
chunked HTTP feeding the same event handlers (reference client-go:
Clientset + SharedInformerFactory + the scheduler's informer wiring in
``pkg/scheduler/eventhandlers.go``), binds through the Binding
subresource, status writes through ``pods/{name}/status``.

Wire discipline (reference ``test/integration/scheduler_perf/util.go:
61-68`` creates clients at QPS/Burst 5000):

- every call charges a client-side token bucket PER OBJECT — a bulk
  request of N pods costs N tokens, so batching never launders rate;
- pooled keep-alive connections with TCP_NODELAY per (client, lane)
  (one urllib-style connection per request stalls ~40 ms each under
  Nagle + delayed ACK; after a transport failure the pool pre-warms a
  replacement under the retry backoff so retries never reconnect cold);
- hot-path writes ship as bulk verbs: creates as ``{Kind}List``, binds
  as ``BindingList`` (POST /bindings), status writes as
  ``PodStatusList`` (POST /statuses, see ``batched_status_writes``);
- the binary codec (``apiserver/codec.py``, the protobuf analog) is
  negotiated for every payload; JSON remains the kubectl/debug wire.
  Watch streams arrive as server-coalesced chunks (a batch of
  per-event pickles per read), decoded and delivered batch-at-a-time.

Reads the scheduler consults once per cycle (services, replica sets,
PDBs, ...) are served from short-TTL caches — the informer-cache
consistency model of the reference, with the TTL standing in for watch
propagation delay.
"""

from __future__ import annotations

import http.client
import json
import random
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.api.serialization import from_wire, to_wire
from kubernetes_tpu.apiserver import codec
from kubernetes_tpu.apiserver.rest import KIND_TO_PLURAL
from kubernetes_tpu.apiserver.store import ADDED, DELETED, MODIFIED, Event
from kubernetes_tpu.client.backoff import Backoff, CircuitBreaker, RetryBudget

# kinds the scheduler's event handlers consume
# (eventhandlers.py handle(); reference addAllEventHandlers)
SCHEDULER_WATCH_KINDS = (
    "Pod", "Node", "Service", "PersistentVolume", "PersistentVolumeClaim",
    "StorageClass", "CSINode",
)


class TokenBucket:
    """Client-side rate limiter (reference client-go rate.Limiter)."""

    def __init__(self, qps: float, burst: Optional[float] = None):
        self.qps = float(qps)
        self.burst = float(burst if burst is not None else qps)
        self._tokens = self.burst
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def charge(self, n: float = 1.0) -> None:
        """Block until n tokens are available, then consume them. A
        charge above the burst is taken in burst-sized installments —
        the bucket can never hold more than ``burst``, so a single-shot
        wait would spin forever (client-go's WaitN just errors there;
        paying the time instead keeps bulk verbs rate-equivalent to N
        singles)."""
        remaining = float(n)
        while remaining > 0:
            take = min(remaining, self.burst)
            while True:
                with self._lock:
                    now = time.monotonic()
                    self._tokens = min(
                        self.burst,
                        self._tokens + (now - self._last) * self.qps)
                    self._last = now
                    if self._tokens >= take:
                        self._tokens -= take
                        break
                    wait = (take - self._tokens) / self.qps
                time.sleep(min(wait, 0.05))
            remaining -= take


class _WatchHandle:
    def __init__(self, client: "RestClusterClient"):
        self._client = client

    def stop(self) -> None:
        self._client._stop_watches()


class _ConnPool:
    """Warm keep-alive connections for one (client, lane). Connections
    are checked out per request and returned on success; a transport
    failure discards the broken connection AND pre-warms a replacement
    during the retry backoff, so the retry itself never reconnects cold
    (reference: client-go's http.Transport connection pool per host)."""

    def __init__(self, host: str, port: int, timeout: float = 60.0,
                 max_idle: int = 8):
        self._host = host
        self._port = port
        self._timeout = timeout
        self.max_idle = max_idle
        self._idle: List[http.client.HTTPConnection] = []
        self._lock = threading.Lock()

    def _connect(self) -> http.client.HTTPConnection:
        conn = http.client.HTTPConnection(self._host, self._port,
                                          timeout=self._timeout)
        conn.connect()
        conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return conn

    def acquire(self) -> http.client.HTTPConnection:
        with self._lock:
            if self._idle:
                return self._idle.pop()
        return self._connect()

    def release(self, conn: http.client.HTTPConnection) -> None:
        with self._lock:
            if len(self._idle) < self.max_idle:
                self._idle.append(conn)
                return
        try:
            conn.close()
        except OSError:
            pass

    @staticmethod
    def discard(conn: Optional[http.client.HTTPConnection]) -> None:
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def prewarm(self, n: int = 1) -> None:
        """Best-effort: open fresh connections into the idle set (called
        under retry backoff so the sleep pays the handshake)."""
        for _ in range(n):
            try:
                conn = self._connect()
            except OSError:
                return
            with self._lock:
                if len(self._idle) < self.max_idle:
                    self._idle.append(conn)
                    conn = None
            if conn is not None:
                _ConnPool.discard(conn)
                return

    def close_all(self) -> None:
        with self._lock:
            idle, self._idle = self._idle, []
        for conn in idle:
            _ConnPool.discard(conn)


class RestClusterClient:
    def __init__(
        self,
        base_url: str,
        token: str = "",
        qps: Optional[float] = None,
        burst: Optional[float] = None,
        binary: bool = True,
        watch_kinds: Tuple[str, ...] = SCHEDULER_WATCH_KINDS,
        cache_ttl: float = 1.0,
        max_retries: int = 5,
        retry_after_cap: float = 2.0,
        backoff: Optional[Backoff] = None,
        retry_budget: Optional[RetryBudget] = None,
        breaker_threshold: int = 5,
        retry_seed: Optional[int] = None,
        flow_id: str = "",
        partition_urls: Optional[List[str]] = None,
    ):
        # partition-aware mode (apiserver/partition.py): one apiserver
        # endpoint per store partition. Single-object calls route by the
        # shared crc32 partition function, lists fan in across the
        # partitions a kind can live in, bulk verbs split by partition
        # and fan out, and watch opens ONE stream per (kind, partition)
        # — the merged delivery preserves per-partition ordering, which
        # is all the store ever guaranteed. ``partition_urls=None``
        # (the default) is exactly the old single-endpoint client.
        urls = [u.rstrip("/") for u in (partition_urls or [base_url])]
        self.base_url = urls[0]
        self.partition_urls = urls
        self.partitions = len(urls)
        self._endpoints: List[Tuple[str, int]] = []
        for u in urls:
            rest = u.split("://", 1)[1]
            host, _, port = rest.partition(":")
            self._endpoints.append((host, int(port or 80)))
        self._host, self._port = self._endpoints[0]
        self.token = token
        # flow distinguisher refinement for the server's API Priority &
        # Fairness layer (X-Flow-Id): several logical tenants behind one
        # identity (the bench harness's anonymous loopback clients) get
        # their own fair-queued flows instead of sharing one. The server
        # honors it only from control-plane/loopback identities —
        # untrusted tenants cannot mint flows to dodge fair queuing.
        self.flow_id = flow_id
        self.binary = binary
        self.watch_kinds = watch_kinds
        self.cache_ttl = cache_ttl
        self.limiter = TokenBucket(qps, burst) if qps else None
        # keep-alive pools per (partition, lane) (mirroring the server's
        # readonly/mutating in-flight lanes): checked out per request,
        # pre-warmed on failure so retries ride an established connection
        self._pools: Dict[Tuple[int, str], _ConnPool] = {
            (p, lane): _ConnPool(host, port)
            for p, (host, port) in enumerate(self._endpoints)
            for lane in ("ro", "rw")
        }
        # lazy executors (_fan_pool, _bind_pool) are created under this
        # lock: fan-out workers can reach the bind pool concurrently,
        # and a lost check-then-create race would leak live threads
        self._pool_init_lock = threading.Lock()
        # active batched-status-write buffers per thread (see
        # batched_status_writes)
        self._status_buffers = threading.local()
        self._ttl_cache: Dict[str, tuple] = {}
        self._stopping = threading.Event()
        self._watch_threads: List[threading.Thread] = []
        # resilience stack: jittered exponential backoff between retries
        # (deterministic under retry_seed for chaos replay), a per-client
        # retry budget so a sick server costs bounded extra load, and a
        # circuit breaker whose listener the scheduler wires to degraded
        # mode (reference client-go's rest.Config backoff + the
        # apiserver's Retry-After contract)
        self.max_retries = int(max_retries)
        self.retry_after_cap = float(retry_after_cap)
        rng = random.Random(retry_seed) if retry_seed is not None else None
        self._backoff = backoff if backoff is not None else \
            Backoff(base=0.05, factor=2.0, cap=2.0, jitter=0.4, rng=rng)
        self._retry_budget = retry_budget if retry_budget is not None \
            else RetryBudget(budget=32.0, refill_per_second=4.0)
        self.breaker = CircuitBreaker(failure_threshold=breaker_threshold)
        # resourceVersion monotonicity watchdog: list RVs per kind must
        # never regress (a WAL-restored server that lost committed
        # revisions would show up here); violations are recorded, never
        # raised — the chaos suite asserts the list stays empty
        self._rv_lock = threading.Lock()
        self._last_rv: Dict[str, int] = {}
        self.rv_regressions: List[Tuple[str, int, int]] = []

    def set_degraded_listener(
            self, listener: Callable[[bool], None]) -> None:
        """``listener(degraded)`` fires when the circuit breaker opens
        (transport to the apiserver is gone) and again when it closes.
        The scheduler uses this to pause binding and resume cleanly."""
        self.breaker.set_listener(listener)

    # -- transport -----------------------------------------------------
    def _drop_conn(self) -> None:
        """Close every pooled keep-alive connection (tests and the
        chaos harness sever live transports after a server kill)."""
        for pool in self._pools.values():
            pool.close_all()

    def _headers(self, body_binary: bool) -> Dict[str, str]:
        h: Dict[str, str] = {}
        if self.binary:
            h["Accept"] = codec.BINARY_CONTENT_TYPE
        h["Content-Type"] = codec.BINARY_CONTENT_TYPE if body_binary \
            else "application/json"
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        if self.flow_id:
            h["X-Flow-Id"] = self.flow_id
        return h

    @staticmethod
    def _note_retry(verb: str, reason: str) -> None:
        # cold path only (a retry already costs a sleep)
        from kubernetes_tpu.metrics.fabric_metrics import fabric_metrics

        fabric_metrics().client_retries_total.inc(verb, reason)

    @staticmethod
    def _observe_delivery(kind: str, events: List[Event]) -> None:
        """Freshness SLI: commit → decode latency for a decoded watch
        batch. One ``observe_many`` per batch (one histogram lock
        round-trip, not one per event); stamp-less events (legacy
        peers, replay synthetics) are skipped."""
        try:
            from kubernetes_tpu.metrics.freshness_metrics import (
                freshness_metrics,
            )

            fm = freshness_metrics()
            if not fm.enabled:
                return
            now = time.time()
            lags = [max(0.0, now - e.ts) for e in events if e.ts]
            if lags:
                fm.watch_delivery_seconds.observe_many(lags, kind)
        except Exception:  # noqa: BLE001 — SLIs must never break watches
            pass

    def _request(self, method: str, path: str, payload: Any = None,
                 charge: float = 1.0, body_binary: Optional[bool] = None,
                 partition: int = 0) -> Tuple[int, Any]:
        if self.limiter is not None:
            self.limiter.charge(charge)
        body_binary = self.binary if body_binary is None else body_binary
        data = None
        if payload is not None:
            data = codec.encode(payload) if body_binary \
                else json.dumps(payload).encode()
        pool = self._pools[(partition,
                            "ro" if method in ("GET", "HEAD") else "rw")]
        headers = self._headers(body_binary)
        if charge > 1:
            # declare the per-object count so the server's APF width
            # estimation charges proportional seats — the wire half of
            # "the token bucket charges per OBJECT": batching must not
            # launder concurrency server-side either
            headers["X-Kubernetes-Request-Items"] = str(int(charge))
        conn: Optional[http.client.HTTPConnection] = None
        attempt = 0
        while True:
            try:
                if conn is None:
                    conn = pool.acquire()
                conn.request(method, path, body=data, headers=headers)
                resp = conn.getresponse()
                raw = resp.read()
            except (http.client.HTTPException, OSError):
                # dropped/reset keep-alive or truncated response (server
                # restart, idle timeout, injected wire fault): retry on
                # a FRESH pooled connection with jittered backoff —
                # requests here are idempotent or conflict-detected
                # server-side. Budget exhaustion surfaces the ORIGINAL
                # transport error. The pool pre-warms a replacement
                # under the backoff sleep so the retry never pays the
                # handshake inside its own window.
                _ConnPool.discard(conn)
                conn = None
                self.breaker.record_failure()
                if attempt >= self.max_retries \
                        or not self._retry_budget.try_spend():
                    raise
                self._note_retry(method, "transport")
                pool.prewarm(1)
                time.sleep(self._backoff.delay(attempt))
                attempt += 1
                continue
            if resp.status in (429, 503) and attempt < self.max_retries \
                    and self._retry_budget.try_spend():
                # overload pushback: honor Retry-After, CAPPED — a
                # misbehaving server advertising an hour must not stall
                # this client unboundedly. A 429 is the flow-control
                # layers (APF or the legacy lanes) talking: overload is
                # NOT outage, so tell the breaker the fabric is healthy
                # — a throttled tenant must never trip degraded mode off
                # the back of interleaved transport blips that pushback
                # would otherwise let accumulate to the threshold. A 503
                # is NOT that: nothing server-side emits it — it comes
                # from fault injection or a genuinely failing server —
                # so it stays breaker-neutral (retried, but never
                # laundered into health during a 503 storm).
                if resp.status == 429:
                    self.breaker.record_success()
                try:
                    advertised = float(
                        resp.headers.get("Retry-After") or 0.0)
                except ValueError:
                    advertised = 0.0
                # attribute the pushback to the rejecting priority
                # level (the server's X-Kubernetes-PF-* headers) so the
                # retry series separates "APF throttled me" from
                # generic 429/503 bursts
                pf_level = resp.headers.get(
                    "X-Kubernetes-PF-PriorityLevel") or ""
                self._note_retry(
                    method,
                    f"apf_{pf_level}" if pf_level
                    else f"http_{resp.status}")
                time.sleep(min(max(advertised,
                                   self._backoff.delay(attempt)),
                               self.retry_after_cap))
                attempt += 1
                continue
            # any HTTP response proves the transport — but a terminal
            # 503 is outage-shaped (fault injection or a genuinely
            # failing server; the flow-control layers only ever answer
            # 429), so it stays breaker-neutral here exactly as in the
            # retry branch above: a sustained 503 storm must still let
            # interleaved transport failures accumulate and open the
            # breaker instead of resetting the count on every response.
            if resp.status != 503:
                self.breaker.record_success()
            if resp.will_close:
                _ConnPool.discard(conn)
            else:
                pool.release(conn)
            ctype = resp.headers.get("Content-Type") or ""
            if ctype.startswith(codec.BINARY_CONTENT_TYPE):
                return resp.status, codec.decode(raw)
            return resp.status, (json.loads(raw) if raw else {})

    @staticmethod
    def _raise_for(code: int, payload: Any) -> None:
        if code < 400:
            return
        msg = payload.get("message", "") if isinstance(payload, dict) \
            else str(payload)
        if code == 404:
            raise KeyError(msg)
        if code in (403, 422):
            raise PermissionError(msg)
        if code == 409:
            raise ValueError(msg)
        raise RuntimeError(f"HTTP {code}: {msg}")

    # -- paths ---------------------------------------------------------
    @staticmethod
    def _path(kind: str, namespace: Optional[str] = None,
              name: Optional[str] = None, sub: Optional[str] = None) -> str:
        plural = KIND_TO_PLURAL.get(kind, kind.lower() + "s")
        p = f"/api/v1/namespaces/{namespace}/{plural}" if namespace \
            else f"/api/v1/{plural}"
        if name:
            p += f"/{name}"
        if sub:
            p += f"/{sub}"
        return p

    def _items(self, payload: Any, kind: str) -> List[Any]:
        items = payload.get("items", [])
        if items and isinstance(items[0], dict):   # JSON wire
            items = [from_wire(i, kind) for i in items]
        return items

    # -- partition routing (apiserver/partition.py's crc32 function —
    # stores, servers and clients must all compute the same shard) ----
    def _pk(self, kind: str, namespace: Optional[str] = None,
            name: Optional[str] = None) -> int:
        if self.partitions == 1:
            return 0
        from kubernetes_tpu.apiserver.partition import partition_for

        return partition_for(kind, namespace, name, self.partitions)

    def _pset(self, kind: str,
              namespace: Optional[str] = None) -> List[int]:
        if self.partitions == 1:
            return [0]
        from kubernetes_tpu.apiserver.partition import partitions_for

        return partitions_for(kind, self.partitions, namespace)

    def _list(self, kind: str, namespace: Optional[str] = None) -> List[Any]:
        parts = self._pset(kind, namespace)

        def one(p: int) -> List[Any]:
            code, payload = self._request(
                "GET", self._path(kind, namespace), partition=p)
            self._raise_for(code, payload)
            return self._items(payload, kind)

        if len(parts) == 1:
            return one(parts[0])
        # the biggest lists in the system (a replica's start() replay
        # of 500k pods) fan in CONCURRENTLY — wall time is the slowest
        # partition, not the sum
        pool = self._fan_out()
        out: List[Any] = []
        for got in pool.map(one, parts):
            out.extend(got)
        return out

    def _list_with_rv(self, kind: str, namespace: Optional[str] = None,
                      partition: Optional[int] = None
                      ) -> Tuple[List[Any], int]:
        """List + consistency RV. With an explicit ``partition`` (the
        per-partition watch loops), exactly that shard is listed and
        the RV is that partition's — the composite-cursor component the
        stream resumes from. Fan-in calls return the max component.
        The RV-monotonicity watchdog is keyed per (kind, partition):
        partitions advance independently, and only the per-partition
        sequence is promised monotonic."""
        out: List[Any] = []
        max_rv = 0
        parts = [partition] if partition is not None \
            else self._pset(kind, namespace)
        for p in parts:
            code, payload = self._request(
                "GET", self._path(kind, namespace), partition=p)
            self._raise_for(code, payload)
            rv = payload.get("resourceVersion")
            if rv is None:
                rv = (payload.get("metadata") or {}).get(
                    "resourceVersion", 0)
            rv = int(rv)
            with self._rv_lock:
                last = self._last_rv.get((kind, p), 0)
                if rv < last:
                    self.rv_regressions.append((kind, last, rv))
                else:
                    self._last_rv[(kind, p)] = rv
            out.extend(self._items(payload, kind))
            max_rv = max(max_rv, rv)
        return out, max_rv

    def _get(self, kind: str, namespace: Optional[str],
             name: str) -> Optional[Any]:
        code, payload = self._request(
            "GET", self._path(kind, namespace, name),
            partition=self._pk(kind, namespace, name))
        if code == 404:
            return None
        self._raise_for(code, payload)
        if isinstance(payload, dict):   # JSON wire
            return from_wire(payload, kind)
        return payload

    def _cached(self, key: str, fetch: Callable[[], Any]) -> Any:
        hit = self._ttl_cache.get(key)
        now = time.monotonic()
        if hit is not None and now - hit[0] < self.cache_ttl:
            return hit[1]
        value = fetch()
        self._ttl_cache[key] = (now, value)
        return value

    # -- hot reads (no cache: the scheduler replays them into its own
    # cache/queue at start, and consults get_pod only on conflicts) ----
    def list_pods(self, namespace: Optional[str] = None) -> List[Any]:
        return self._list("Pod", namespace)

    def list_nodes(self) -> List[Any]:
        return self._list("Node")

    def get_pod(self, namespace: str, name: str) -> Optional[Any]:
        return self._get("Pod", namespace, name)

    # -- kubelet surface (kubemark hollow nodes over the REST fabric:
    # node registration, heartbeat leases, pod lifecycle writes) -------
    def get_node(self, name: str) -> Optional[Any]:
        return self._get("Node", None, name)

    def add_node(self, node) -> None:
        """Upsert like ``store.add_node`` (kubelet registration is an
        upsert: re-registration after a restart must not 409)."""
        try:
            self.create_object("Node", node)
        except ValueError:
            self.update_object("Node", node)

    def update_node(self, node) -> None:
        self.update_object("Node", node)

    def delete_node(self, name: str) -> None:
        code, payload = self._request(
            "DELETE", self._path("Node", None, name),
            partition=self._pk("Node", None, name))
        if code >= 400 and code != 404:
            self._raise_for(code, payload)

    def create_pod(self, pod) -> Any:
        """Single-pod create (the kubelet's mirror-pod path)."""
        return self.create_object("Pod", pod)

    def set_pod_phase(self, namespace: str, name: str, phase: str,
                      pod_ip: str = "", host_ip: str = "") -> bool:
        status: Dict[str, Any] = {}
        if phase:
            status["phase"] = phase
        if pod_ip:
            status["podIP"] = pod_ip
        if host_ip:
            status["hostIP"] = host_ip
        code, payload = self._request(
            "PUT", self._path("Pod", namespace, name, "status"),
            {"status": status}, body_binary=False,
            partition=self._pk("Pod", namespace))
        if code == 404:
            return False
        self._raise_for(code, payload)
        return True

    def try_acquire_or_renew(self, name: str, holder: str, now: float,
                             duration: float) -> bool:
        """Heartbeat/leader lease over REST (POST
        .../leases/{name}/acquire — rest.py's lease verb; the
        in-process ``_Lease`` CAS, made remote). ``now`` is evaluated
        server-side (one clock must arbitrate)."""
        code, payload = self._request(
            "POST", f"/api/v1/leases/{name}/acquire",
            {"holder": holder, "duration": duration},
            body_binary=False)
        self._raise_for(code, payload)
        return bool(payload.get("acquired"))

    def lease_holder(self, name: str) -> Optional[str]:
        obj = self._get("Lease", "kube-system", name)
        return getattr(obj, "holder_identity", None) if obj is not None \
            else None

    # -- cycle reads (TTL-cached: informer-cache consistency) ----------
    def list_services(self, namespace: str) -> List[Any]:
        return self._cached(f"svc/{namespace}",
                            lambda: self._list("Service", namespace))

    def list_replication_controllers(self, namespace: str) -> List[Any]:
        return self._cached(
            f"rc/{namespace}",
            lambda: self._list("ReplicationController", namespace))

    def list_replica_sets(self, namespace: str) -> List[Any]:
        return self._cached(f"rs/{namespace}",
                            lambda: self._list("ReplicaSet", namespace))

    def list_stateful_sets(self, namespace: str) -> List[Any]:
        return self._cached(f"sts/{namespace}",
                            lambda: self._list("StatefulSet", namespace))

    def list_pdbs(self) -> List[Any]:
        return self._cached("pdbs",
                            lambda: self._list("PodDisruptionBudget"))

    def list_pvs(self) -> List[Any]:
        return self._cached("pvs", lambda: self._list("PersistentVolume"))

    def list_csi_nodes(self) -> List[Any]:
        return self._cached("csinodes", lambda: self._list("CSINode"))

    def get_pvc(self, namespace: str, name: str) -> Optional[Any]:
        return self._get("PersistentVolumeClaim", namespace, name)

    def get_pv(self, name: str) -> Optional[Any]:
        return self._get("PersistentVolume", None, name)

    def get_storage_class(self, name: str) -> Optional[Any]:
        return self._cached(f"sc/{name}",
                            lambda: self._get("StorageClass", None, name))

    def get_csi_node(self, name: str) -> Optional[Any]:
        return self._get("CSINode", None, name)

    # -- binds ---------------------------------------------------------
    def bind(self, namespace: str, name: str, uid: str,
             node_name: str) -> None:
        code, payload = self._request(
            "POST", self._path("Pod", namespace, name, "binding"),
            {"kind": "Binding", "uid": uid, "target": {"name": node_name}},
            body_binary=False, partition=self._pk("Pod", namespace),
        )
        self._raise_for(code, payload)

    # past this size, a bulk bind splits across two pipelined requests:
    # the client pickles chunk k+1 while the server applies chunk k —
    # overlap a single blocking round trip cannot have
    _BIND_SPLIT = 1024

    def _fan_out(self):
        """Shared executor for per-partition bulk-verb fan-out (bulk
        verbs split by partition and ship concurrently — each
        partition's server applies its slice under its own lock/GIL).
        Creation is serialized: fan-out workers themselves reach the
        split-bind pool, and a check-then-create race would leak a
        live executor."""
        from concurrent.futures import ThreadPoolExecutor

        with self._pool_init_lock:
            pool = getattr(self, "_fan_pool", None)
            if pool is None:
                pool = self._fan_pool = ThreadPoolExecutor(
                    max_workers=max(2, min(self.partitions, 8)),
                    thread_name_prefix="partition-fan")
        return pool

    def check_partition_topology(self) -> None:
        """Validate that every configured endpoint serves the partition
        index this client will route to it (GET
        /api/v1/partitiontopology) — a client built with shuffled or
        wrong-count URLs must fail HERE, loudly, not silently read
        half-empty shards. Servers predating the endpoint (404) are
        skipped best-effort."""
        for i in range(self.partitions):
            code, topo = self._request(
                "GET", "/api/v1/partitiontopology", partition=i)
            if code == 404:
                continue
            if code != 200 or not isinstance(topo, dict):
                raise RuntimeError(
                    f"partition {i} topology probe failed: HTTP {code}")
            if topo.get("partition") != i \
                    or topo.get("partitions") != self.partitions:
                raise RuntimeError(
                    f"partition_urls[{i}] ({self.partition_urls[i]}) "
                    f"serves partition {topo.get('partition')} of "
                    f"{topo.get('partitions')}, not {i} of "
                    f"{self.partitions} — misconfigured routing")

    def _group_by_partition(self, items, key_fn):
        """[(partition, [(orig_index, item), ...]), ...] preserving
        per-partition order."""
        groups: Dict[int, list] = {}
        for i, item in enumerate(items):
            groups.setdefault(key_fn(item), []).append((i, item))
        return sorted(groups.items())

    def _fan_by_partition(self, items, key_fn, call_fn):
        """The bulk-verb fan-out scaffold, once: split positional
        ``items`` by partition, run ``call_fn(partition, slice)`` per
        group (concurrently when several partitions are involved), and
        merge each slice's positional results back into item order."""
        results: List[Any] = [None] * len(items)
        groups = self._group_by_partition(items, key_fn)
        if len(groups) == 1:
            p, entries = groups[0]
            outs = [(entries, call_fn(p, [it for _, it in entries]))]
        else:
            pool = self._fan_out()
            futures = [
                (entries, pool.submit(call_fn, p,
                                      [it for _, it in entries]))
                for p, entries in groups
            ]
            outs = [(entries, fut.result()) for entries, fut in futures]
        for entries, got in outs:
            for (i, _item), r in zip(entries, got):
                results[i] = r
        return results

    def bind_many(
        self, bindings: List[Tuple[str, str, str, str]]
    ) -> List[Optional[Exception]]:
        """Bulk POST ../bindings; per-item failures come back
        positionally — the exact contract of store.bind_many. With a
        partitioned fabric the batch splits by the pod's partition and
        the slices fan out concurrently."""
        if not bindings:
            return []
        if self.partitions == 1:
            return self._bind_partition(0, bindings)
        return self._fan_by_partition(
            bindings, lambda b: self._pk("Pod", b[0]),
            self._bind_partition)

    def _bind_partition(
        self, partition: int, bindings: List[Tuple[str, str, str, str]]
    ) -> List[Optional[Exception]]:
        if len(bindings) > self._BIND_SPLIT:
            from concurrent.futures import ThreadPoolExecutor

            with self._pool_init_lock:
                pool = getattr(self, "_bind_pool", None)
                if pool is None:
                    pool = self._bind_pool = ThreadPoolExecutor(
                        max_workers=2, thread_name_prefix="bind-many")
            mid = len(bindings) // 2
            left = pool.submit(self._bind_chunk, bindings[:mid],
                               partition)
            right = self._bind_chunk(bindings[mid:], partition)
            return left.result() + right
        return self._bind_chunk(bindings, partition)

    def _bind_chunk(
        self, bindings: List[Tuple[str, str, str, str]],
        partition: int = 0,
    ) -> List[Optional[Exception]]:
        if self.binary:
            payload: Any = {"kind": "BindingList",
                            "items": [tuple(b) for b in bindings]}
        else:
            payload = {"kind": "BindingList", "items": [
                {"namespace": ns, "name": n, "uid": u,
                 "target": {"name": node}}
                for ns, n, u, node in bindings
            ]}
        code, resp = self._request("POST", "/api/v1/bindings", payload,
                                   charge=len(bindings),
                                   partition=partition)
        if code >= 400:
            err = RuntimeError(
                resp.get("message", f"HTTP {code}")
                if isinstance(resp, dict) else f"HTTP {code}")
            return [err] * len(bindings)
        errors: List[Optional[Exception]] = [None] * len(bindings)
        for f in resp.get("failures", ()):
            exc = KeyError(f["message"]) if f.get("code") == 404 \
                else ValueError(f["message"])
            errors[f["index"]] = exc
        return errors

    # -- pod status / lifecycle writes ---------------------------------
    def _put_status(self, namespace: str, name: str, status: dict) -> None:
        buf = getattr(self._status_buffers, "buf", None)
        if buf is not None:
            # inside a batched_status_writes scope: coalesce — the
            # items apply in order at scope exit as ONE bulk request
            buf.append({"namespace": namespace, "name": name,
                        "status": status})
            return
        code, payload = self._request(
            "PUT", self._path("Pod", namespace, name, "status"),
            {"status": status}, body_binary=False,
            partition=self._pk("Pod", namespace))
        if code == 404:
            return   # pod deleted under us: store semantics are no-op
        self._raise_for(code, payload)

    def write_pod_statuses(self, updates: List[dict]
                           ) -> List[Optional[Exception]]:
        """Bulk POST /api/v1/statuses (PodStatusList): N status writes,
        one round trip per PARTITION (the batch splits by the pod's
        partition and fans out), positional failures. Each item is
        ``{"namespace", "name", "status": {...}}`` with the exact
        per-item semantics of PUT pods/{name}/status; the token bucket
        charges per ITEM, so bulk status writes stay rate-equivalent to
        N singles. 404s are None (pod deleted under us), matching
        ``_put_status``."""
        if not updates:
            return []
        if self.partitions == 1:
            return self._statuses_partition(0, list(updates))
        return self._fan_by_partition(
            updates, lambda u: self._pk("Pod", u.get("namespace")),
            self._statuses_partition)

    def _statuses_partition(self, partition: int, updates: List[dict]
                            ) -> List[Optional[Exception]]:
        code, resp = self._request(
            "POST", "/api/v1/statuses",
            {"kind": "PodStatusList", "items": updates},
            charge=len(updates), body_binary=False, partition=partition)
        if code >= 400:
            err = RuntimeError(
                resp.get("message", f"HTTP {code}")
                if isinstance(resp, dict) else f"HTTP {code}")
            return [err] * len(updates)
        errors: List[Optional[Exception]] = [None] * len(updates)
        for f in resp.get("failures", ()):
            if f.get("code") == 404:
                continue   # pod deleted under us: single-PUT no-op
            errors[f["index"]] = PermissionError(f["message"]) \
                if f.get("code") in (403, 422) \
                else RuntimeError(f["message"])
        return errors

    def batched_status_writes(self):
        """Scope that coalesces this THREAD's pod-status writes
        (conditions, nominatedNodeName, phase) into one bulk
        ``/statuses`` request flushed at exit — the mass-decline path
        writes thousands of PodScheduled=False conditions per batch,
        and per-object round trips there serialize the whole commit
        loop. Writes become visible at scope exit; the callers that use
        this are already best-effort about status visibility."""
        import contextlib

        @contextlib.contextmanager
        def scope():
            if getattr(self._status_buffers, "buf", None) is not None:
                # nested scope: the outer one owns the flush
                yield
                return
            buf: List[dict] = []
            self._status_buffers.buf = buf
            try:
                yield
            finally:
                self._status_buffers.buf = None
                if buf:
                    try:
                        self.write_pod_statuses(buf)
                    except Exception:  # noqa: BLE001 — best-effort,
                        # like the per-object writes it replaces
                        pass

        return scope()

    def patch_pod_condition(self, namespace: str, name: str,
                            condition) -> None:
        self._put_status(namespace, name, {"conditions": [{
            "type": condition.type, "status": condition.status,
            "reason": condition.reason, "message": condition.message,
        }]})

    def set_nominated_node_name(self, namespace: str, name: str,
                                node: str) -> None:
        self._put_status(namespace, name, {"nominatedNodeName": node})

    def clear_nominated_node_name(self, namespace: str, name: str) -> None:
        self._put_status(namespace, name, {"nominatedNodeName": ""})

    def delete_pod(self, namespace: str, name: str) -> None:
        code, payload = self._request(
            "DELETE", self._path("Pod", namespace, name),
            partition=self._pk("Pod", namespace))
        if code >= 400 and code != 404:
            self._raise_for(code, payload)

    def delete_pods(self, keys: List[Tuple[str, str]]) -> None:
        for namespace, name in keys:
            self.delete_pod(namespace, name)

    # -- PV binding (volume-binding plugin / commit binder) ------------
    # Scheduler-side assume/revert are CLIENT-LOCAL bookkeeping in the
    # reference (the volume binder's AssumeCache); over REST they have
    # no server half, and the commit-time bind goes through object
    # updates. The REST bench families exercise bound-claim and WFC
    # flows through these four.
    def assume_pv_bound(self, pv_name: str, pvc_key: str) -> None:
        raise NotImplementedError(
            "assume_pv_bound is store-local; run PV-assume workloads "
            "against the in-process store or extend the REST surface")

    def revert_assumed_pv(self, pv_name: str) -> None:
        raise NotImplementedError("see assume_pv_bound")

    def bind_pv(self, pv_name: str, pvc_namespace: str,
                pvc_name: str) -> bool:
        raise NotImplementedError("see assume_pv_bound")

    def unbind_pv(self, pv_name: str, pvc_namespace: str,
                  pvc_name: str) -> None:
        raise NotImplementedError("see assume_pv_bound")

    # -- generic objects (event recorder, extenders) -------------------
    def create_object(self, kind: str, obj) -> Any:
        ns = getattr(obj.metadata, "namespace", None)
        code, payload = self._request(
            "POST", self._path(kind, ns),
            obj if self.binary else to_wire(obj),
            partition=self._pk(kind, ns, obj.metadata.name))
        self._raise_for(code, payload)
        return obj

    def create_objects_bulk(self, kind: str, objs: List[Any]) -> int:
        if not objs:
            return 0
        if self.partitions == 1:
            return self._create_bulk_partition(0, kind, objs)
        # ride the shared scaffold by spreading each slice's created
        # COUNT over per-item 0/1 flags (only the sum is contractual)
        def create_slice(p: int, group: List[Any]) -> List[int]:
            created = self._create_bulk_partition(p, kind, group)
            return [1] * created + [0] * (len(group) - created)

        flags = self._fan_by_partition(
            objs,
            lambda o: self._pk(
                kind, getattr(o.metadata, "namespace", None),
                o.metadata.name),
            create_slice)
        return sum(flags)

    def _create_bulk_partition(self, partition: int, kind: str,
                               objs: List[Any]) -> int:
        # a batch spanning namespaces must POST the cluster-scoped
        # collection (the path namespace overrides per-item namespaces
        # server-side)
        ns = getattr(objs[0].metadata, "namespace", None)
        if ns is not None and any(
                getattr(o.metadata, "namespace", None) != ns
                for o in objs):
            ns = None
        payload = {"kind": f"{kind}List",
                   "items": objs if self.binary
                   else [to_wire(o) for o in objs]}
        code, resp = self._request("POST", self._path(kind, ns), payload,
                                   charge=len(objs), partition=partition)
        self._raise_for(code, resp)
        return resp.get("created", 0)

    def update_object(self, kind: str, obj,
                      expect_rv: Optional[str] = None) -> Any:
        ns = getattr(obj.metadata, "namespace", None)
        code, payload = self._request(
            "PUT", self._path(kind, ns, obj.metadata.name),
            obj if self.binary else to_wire(obj),
            partition=self._pk(kind, ns, obj.metadata.name))
        self._raise_for(code, payload)
        return obj

    def get_object(self, kind: str, namespace: str, name: str):
        return self._get(
            kind, namespace if namespace else None, name)

    def list_objects(self, kind: str,
                     namespace: Optional[str] = None) -> List[Any]:
        """Generic list (the informer factory's fallback surface):
        fans in across the partitions the kind can live in."""
        return self._list(kind, namespace)

    def prune_expired_events(self, now: Optional[float] = None) -> int:
        return 0   # server-side Events TTL owns expiry over REST

    # -- watch ---------------------------------------------------------
    def watch(self, fn: Callable[[Event], None],
              batch_fn: Optional[Callable[[List[Event]], None]] = None
              ) -> _WatchHandle:
        """List+Watch every scheduler kind over chunked HTTP, delivering
        through the same (fn, batch_fn) contract as store.watch. Binary
        streams arrive as server-batched frames — one frame, one
        batch_fn call (the store's own batched dispatch, preserved over
        the wire). Against a partitioned fabric this opens ONE stream
        per (kind, partition) and merges: each stream is its own
        reflector with its own resume cursor component and relist
        scope, so a torn/stalled stream on one partition never delays
        (or forces a relist of) another."""
        self._stopping.clear()
        for kind in self.watch_kinds:
            for p in self._pset(kind):
                t = threading.Thread(
                    target=self._watch_loop, args=(kind, p, fn, batch_fn),
                    daemon=True, name=f"watch-{kind}-p{p}")
                t.start()
                self._watch_threads.append(t)
        return _WatchHandle(self)

    def _stop_watches(self) -> None:
        self._stopping.set()

    def _watch_loop(self, kind: str, partition: int, fn, batch_fn) -> None:
        first = True
        # objects this stream has shown the consumer, for reflector
        # Replace semantics on reconnect: (ns, name) -> last-seen obj.
        # Per (kind, partition): a partition stream relists only ITS
        # slice, so the diff is against what THIS stream showed.
        known: Dict[tuple, Any] = {}

        def key_of(obj) -> tuple:
            return (getattr(obj.metadata, "namespace", ""),
                    obj.metadata.name)

        def deliver(events: List[Event]) -> None:
            for e in events:
                if e.type == DELETED:
                    known.pop(key_of(e.obj), None)
                else:
                    known[key_of(e.obj)] = e.obj
            if batch_fn is not None:
                batch_fn(events)
            else:
                for e in events:
                    fn(e)

        while not self._stopping.is_set():
            try:
                objs, rv = self._list_with_rv(kind, partition=partition)
                if first:
                    # Scheduler.start() replays the first list itself;
                    # this stream only has to remember what exists
                    known.update((key_of(o), o) for o in objs)
                    first = False
                else:
                    # reflector Replace: a dropped watch lost an
                    # unknowable window — deliver only the diff against
                    # what this stream already showed the consumer
                    # (replace_diff: dedupe unchanged, MODIFIED with
                    # last-known old, synthetic DELETED for vanished)
                    from kubernetes_tpu.client.informers import (
                        replace_diff,
                    )
                    from kubernetes_tpu.metrics.fabric_metrics import (
                        fabric_metrics,
                    )

                    fabric_metrics().client_relists_total.inc(kind)
                    events = replace_diff(
                        kind, dict(known),
                        {key_of(o): o for o in objs})
                    if events:
                        deliver(events)
                self._stream_watch(kind, rv, deliver,
                                   partition=partition)
            except (http.client.HTTPException, OSError, RuntimeError):
                pass
            if self._stopping.is_set():
                return
            time.sleep(0.2)   # relist-and-rewatch (reflector restart)

    def _stream_watch(self, kind: str, rv: int, deliver,
                      partition: int = 0) -> None:
        plural = KIND_TO_PLURAL.get(kind, kind.lower() + "s")
        host, port = self._endpoints[partition]
        conn = http.client.HTTPConnection(host, port, timeout=300)
        conn.connect()
        conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        headers = {}
        if self.binary:
            headers["Accept"] = codec.BINARY_CONTENT_TYPE
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        if self.flow_id:
            headers["X-Flow-Id"] = self.flow_id
        try:
            conn.request(
                "GET", f"/api/v1/{plural}?watch=1&resourceVersion={rv}",
                headers=headers)
            resp = conn.getresponse()
            if resp.status != 200:
                resp.read()
                if resp.status == 410:
                    # expired resourceVersion (watch-cache compaction or
                    # a server restart): the caller's relist IS the
                    # 410-Gone recovery; count it for observability
                    self._note_retry("WATCH", "http_410")
                return
            binary = (resp.headers.get("Content-Type") or "").startswith(
                codec.BINARY_CONTENT_TYPE)
            while not self._stopping.is_set():
                if binary:
                    try:
                        batch = codec.read_frame(resp)
                    except Exception:  # noqa: BLE001 — torn outer frame
                        # the stream was cut mid-frame (injected
                        # truncation, server death): relist, exactly
                        # like the JSON torn-line path below
                        return
                    if batch is None:
                        return
                    # a coalesced chunk carries per-event pickles
                    # (encoded once server-side, shared across
                    # watchers); decode each into the same Event shape.
                    # The 4th element is the store-commit timestamp
                    # (freshness SLI); legacy 3-tuples decode with no
                    # stamp.
                    try:
                        events = []
                        for item in batch:
                            if isinstance(item, (bytes, bytearray)):
                                item = codec.decode(item)
                            if len(item) == 4:
                                t, obj, old, ts = item
                            else:
                                (t, obj, old), ts = item, 0.0
                            events.append(Event(t, kind, obj, old, ts))
                    except Exception:  # noqa: BLE001 — torn event
                        return
                else:
                    line = resp.readline()
                    if not line:
                        return
                    try:
                        msg = json.loads(line)
                        obj = from_wire(msg["object"], kind)
                    except (ValueError, KeyError, TypeError):
                        # torn frame: the stream was cut mid-line
                        # (injected truncation, server death) — relist.
                        # Scoped to PARSING only: a consumer error in
                        # deliver() must surface, not loop forever.
                        return
                    events = [Event(msg["type"], kind, obj,
                                    ts=float(msg.get("commitTs") or 0.0))]
                self._observe_delivery(kind, events)
                deliver(events)
        finally:
            try:
                conn.close()
            except OSError:
                pass
